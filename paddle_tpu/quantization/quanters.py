"""Fake quantizers (reference: python/paddle/quantization/quanters/abs_max.py
FakeQuanterWithAbsMaxObserver — simulated quant in forward, STE backward)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .._core.autograd import apply
from .._core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops._registry import as_tensor


def fake_quant(x, scale, bit_length=8):
    """Simulated symmetric quantization with straight-through gradient:
    x + sg(round(clip(x/s)) * s - x)."""
    x = as_tensor(x)
    qmax = float(2 ** (bit_length - 1) - 1)

    def f(v, s):
        s = jnp.maximum(jnp.abs(s), 1e-8)
        q = jnp.clip(jnp.round(v / s * qmax), -qmax, qmax) * s / qmax
        return v + jax.lax.stop_gradient(q - v)
    return apply(f, x, as_tensor(scale), name="fake_quant")


def quant(x, scale, bit_length=8):
    x = as_tensor(x)
    qmax = float(2 ** (bit_length - 1) - 1)
    return apply(
        lambda v, s: jnp.clip(jnp.round(v / jnp.maximum(jnp.abs(s), 1e-8)
                                        * qmax), -qmax, qmax)
        .astype(jnp.int8),
        x, as_tensor(scale), name="quant")


def dequant(x, scale, bit_length=8):
    x = as_tensor(x)
    qmax = float(2 ** (bit_length - 1) - 1)
    return apply(
        lambda v, s: v.astype(jnp.float32) * jnp.abs(s) / qmax,
        x, as_tensor(scale), name="dequant")


from .observers import BaseObserver


class BaseQuanter(BaseObserver):
    """reference: quantization/base_quanter.py — trainable
    fake-quant layers extend the observer protocol."""


class FakeQuanterWithAbsMaxObserver(BaseQuanter):
    """Activation fake-quant with moving-average abs-max scale
    (reference: quanters/abs_max.py; static counterpart
    fake_quantize_moving_average_abs_max op)."""

    def __init__(self, moving_rate=0.9, bit_length=8, dtype="float32",
                 name=None, quant_on_weight=False):
        super().__init__()
        self._moving_rate = moving_rate
        self._bit_length = bit_length
        import jax.numpy as _j
        self.register_buffer("scale", Tensor(_j.ones(()), _internal=True))
        self._initialized = False

    def forward(self, x):
        x = as_tensor(x)
        if self.training:
            cur = float(jnp.max(jnp.abs(x._value)))
            if not self._initialized:
                new = cur if cur > 0 else 1.0
                self._initialized = True
            else:
                r = self._moving_rate
                new = r * float(self.scale._value) + (1 - r) * cur
            self.scale.set_value(jnp.asarray(new, jnp.float32))
        return fake_quant(x, self.scale, self._bit_length)

    def scales(self):
        return self.scale

    def bit_length(self):
        return self._bit_length
