"""paddle.quantization parity (reference: python/paddle/quantization/ —
QuantConfig config.py, QAT qat.py, PTQ ptq.py, observers
observers/abs_max.py, quanters quanter.py FakeQuanterWithAbsMaxObserver,
factory.py).

TPU-native: fake-quant uses the straight-through estimator expressed as
``x + stop_gradient(q(x) - x)`` so it runs under jit and trains; int8
simulation targets the MXU's int8 mode for deployment.
"""
from .config import QuantConfig  # noqa: F401
from .observers import (  # noqa: F401
    AbsmaxObserver, ObserverFactory, EMAObserver, HistObserver, KLObserver,
    AbsMaxChannelWiseWeightObserver, GroupWiseWeightObserver,
)
from .quanters import (  # noqa: F401
    FakeQuanterWithAbsMaxObserver, quant, dequant, fake_quant,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401

# base classes + decorator (reference: quantization/factory.py quanter,
# base_observer.py BaseObserver, base_quanter.py BaseQuanter)
from .observers import BaseObserver  # noqa: E402,F401
from .quanters import BaseQuanter  # noqa: E402,F401


def quanter(class_name):
    """reference: quantization/factory.py quanter — decorator registering
    a quanter factory under ``class_name`` for QuantConfig lookup."""
    def deco(cls):
        import sys as _sys
        mod = _sys.modules[cls.__module__]
        setattr(mod, class_name, cls)
        globals()[class_name] = cls
        return cls
    return deco
