"""paddle.quantization parity (reference: python/paddle/quantization/ —
QuantConfig config.py, QAT qat.py, PTQ ptq.py, observers
observers/abs_max.py, quanters quanter.py FakeQuanterWithAbsMaxObserver,
factory.py).

TPU-native: fake-quant uses the straight-through estimator expressed as
``x + stop_gradient(q(x) - x)`` so it runs under jit and trains; int8
simulation targets the MXU's int8 mode for deployment.
"""
from .config import QuantConfig  # noqa: F401
from .observers import AbsmaxObserver, ObserverFactory  # noqa: F401
from .quanters import (  # noqa: F401
    FakeQuanterWithAbsMaxObserver, quant, dequant, fake_quant,
)
from .qat import QAT  # noqa: F401
from .ptq import PTQ  # noqa: F401
