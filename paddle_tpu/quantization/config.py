"""QuantConfig (reference: python/paddle/quantization/config.py) — maps
layers/types/names to (activation quanter, weight quanter) policies."""
from __future__ import annotations

from typing import Dict, List, Optional, Tuple


class QuantConfig:
    def __init__(self, activation=None, weight=None):
        self._global = (activation, weight)
        self._by_layer: List[Tuple[object, object, object]] = []
        self._by_type: Dict[type, Tuple[object, object]] = {}
        self._by_name: Dict[str, Tuple[object, object]] = {}

    def add_layer_config(self, layer, activation=None, weight=None):
        layers = layer if isinstance(layer, (list, tuple)) else [layer]
        for l in layers:
            self._by_layer.append((l, activation, weight))

    def add_type_config(self, layer_type, activation=None, weight=None):
        types = layer_type if isinstance(layer_type, (list, tuple)) \
            else [layer_type]
        for t in types:
            self._by_type[t] = (activation, weight)

    def add_name_config(self, layer_name, activation=None, weight=None):
        names = layer_name if isinstance(layer_name, (list, tuple)) \
            else [layer_name]
        for n in names:
            self._by_name[n] = (activation, weight)

    def policy_for(self, name: str, layer) -> Tuple[object, object]:
        for l, a, w in self._by_layer:
            if l is layer:
                return a, w
        if name in self._by_name:
            return self._by_name[name]
        for t, pol in self._by_type.items():
            if isinstance(layer, t):
                return pol
        return self._global
