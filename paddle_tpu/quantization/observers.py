"""Observers (reference: python/paddle/quantization/observers/abs_max.py
AbsmaxObserver + factory.py ObserverFactory)."""
from __future__ import annotations

import jax.numpy as jnp

from .._core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops._registry import as_tensor


class BaseObserver(Layer):
    """reference: quantization/base_observer.py — the extension point for
    statistic-collecting layers: implement forward() (collect + pass
    through) and scales()."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class ObserverFactory:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def _instance(self, layer):
        return self._cls(**self._kwargs)


class AbsmaxObserver(ObserverFactory):
    """Collects running abs-max during calibration (PTQ)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits=quant_bits)
        self._cls = AbsmaxObserverLayer


class AbsmaxObserverLayer(BaseObserver):
    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        x = as_tensor(x)
        self._max = max(self._max, float(jnp.max(jnp.abs(x._value))))
        return x

    def scales(self):
        return Tensor(jnp.asarray(self._max or 1.0), _internal=True)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def zero_points(self):
        return Tensor(jnp.zeros(()), _internal=True)
