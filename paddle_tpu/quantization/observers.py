"""Observers (reference: python/paddle/quantization/observers/abs_max.py
AbsmaxObserver, observers/groupwise.py GroupWiseWeightObserver,
factory.py ObserverFactory; histogram/KL calibration re-designs the
static stack python/paddle/static/quantization/cal_kl_threshold.py +
post_training_quantization.py hist/KL/percent algorithms).

TPU-native split of labor: per-batch statistics (absmax, histograms) are
single jnp reductions on device; the calibration math (EMA, percentile
search, KL threshold search) is host-side numpy over the collected
statistics — it runs once, between steps, and never enters a compiled
program."""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from .._core.tensor import Tensor
from ..nn.layer.layers import Layer
from ..ops._registry import as_tensor


class BaseObserver(Layer):
    """reference: quantization/base_observer.py — the extension point for
    statistic-collecting layers: implement forward() (collect + pass
    through) and scales()."""

    def scales(self):
        raise NotImplementedError

    def zero_points(self):
        return None


class ObserverFactory:
    def __init__(self, **kwargs):
        self._kwargs = kwargs

    def _instance(self, layer):
        return self._cls(**self._kwargs)


class AbsmaxObserver(ObserverFactory):
    """Collects running abs-max during calibration (PTQ)."""

    def __init__(self, quant_bits=8):
        super().__init__(quant_bits=quant_bits)
        self._cls = AbsmaxObserverLayer


class AbsmaxObserverLayer(BaseObserver):
    def __init__(self, quant_bits=8):
        super().__init__()
        self._quant_bits = quant_bits
        self._max = 0.0

    def forward(self, x):
        x = as_tensor(x)
        self._max = max(self._max, float(jnp.max(jnp.abs(x._value))))
        return x

    def scales(self):
        return Tensor(jnp.asarray(self._max or 1.0), _internal=True)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1

    def zero_points(self):
        return Tensor(jnp.zeros(()), _internal=True)


class EMAObserver(ObserverFactory):
    """Exponential-moving-average abs-max (reference: the moving-average
    flavor of abs_max used by PTQ activation calibration)."""

    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__(quant_bits=quant_bits, moving_rate=moving_rate)
        self._cls = EMAObserverLayer


class EMAObserverLayer(BaseObserver):
    def __init__(self, quant_bits=8, moving_rate=0.9):
        super().__init__()
        self._quant_bits = quant_bits
        self._rate = moving_rate
        self._ema = None

    def forward(self, x):
        x = as_tensor(x)
        cur = float(jnp.max(jnp.abs(x._value)))
        self._ema = cur if self._ema is None else (
            self._rate * self._ema + (1 - self._rate) * cur)
        return x

    def scales(self):
        return Tensor(jnp.asarray(self._ema or 1.0), _internal=True)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1


class _HistogramState:
    """Running |x| histogram with proportional range growth: when a batch
    exceeds the current range, old bins are merged into the wider bins
    (old bin i -> new bin i // factor) so earlier batches keep their
    weight — the rebinning trick of the static PTQ hist collector."""

    def __init__(self, bins=2048):
        self.bins = bins
        self.hist = np.zeros(bins, np.float64)
        self.amax = None

    def update(self, absx: np.ndarray):
        bmax = float(absx.max()) if absx.size else 0.0
        if bmax == 0.0 and self.amax is None:
            return
        if self.amax is None:
            self.amax = bmax
        elif bmax > self.amax:
            factor = int(np.ceil(bmax / self.amax))
            merged = np.zeros(self.bins, np.float64)
            idx = np.arange(self.bins) // factor
            np.add.at(merged, idx, self.hist)
            self.hist = merged
            self.amax *= factor
        h, _ = np.histogram(absx, bins=self.bins, range=(0.0, self.amax))
        self.hist += h

    @property
    def bin_width(self) -> float:
        return (self.amax or 1.0) / self.bins


class HistObserver(ObserverFactory):
    """Percentile-of-histogram scale (reference: the 'hist' algo of
    static PostTrainingQuantization, hist_percent)."""

    def __init__(self, quant_bits=8, bins=2048, percent=0.99999):
        super().__init__(quant_bits=quant_bits, bins=bins, percent=percent)
        self._cls = HistObserverLayer


class HistObserverLayer(BaseObserver):
    def __init__(self, quant_bits=8, bins=2048, percent=0.99999):
        super().__init__()
        self._quant_bits = quant_bits
        self._percent = percent
        self._state = _HistogramState(bins)

    def forward(self, x):
        x = as_tensor(x)
        self._state.update(np.abs(np.asarray(x._value, np.float32)).ravel())
        return x

    def scales(self):
        st = self._state
        if st.amax is None:
            return Tensor(jnp.asarray(1.0), _internal=True)
        cum = np.cumsum(st.hist)
        total = cum[-1]
        if total <= 0:
            return Tensor(jnp.asarray(st.amax), _internal=True)
        i = int(np.searchsorted(cum, self._percent * total))
        scale = (i + 1) * st.bin_width
        return Tensor(jnp.asarray(scale), _internal=True)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1


def _kl_divergence_threshold(hist: np.ndarray, levels: int) -> int:
    """Index of the clip bin minimizing KL(P || quantize(P, levels)) —
    the entropy-calibration search (reference:
    static/quantization/cal_kl_threshold.py cal_kl_threshold; algorithm
    re-derived, implementation original)."""
    n = len(hist)
    if n <= levels:
        return n
    best_i, best_kl = n, np.inf
    total = hist.sum()
    if total <= 0:
        return n
    # start the search at half the range (reference: cal_kl_threshold's
    # starting_iter = (bins-1)*0.5) — candidates below that degenerate
    # toward Q == P (tiny merge groups), which always "wins" with KL 0
    # while clipping almost everything
    start = max(levels, n // 2)
    for i in range(start, n + 1):
        p = hist[:i].astype(np.float64).copy()
        p[i - 1] += hist[i:].sum()          # outliers clip into last bin
        # reference distribution, smoothed where empty
        p_nz = p > 0
        # quantized distribution: i bins grouped into `levels` buckets;
        # each bucket's mass spreads uniformly over its NONZERO src bins
        group = (np.arange(i) * levels) // i
        bucket_sum = np.bincount(group, weights=p, minlength=levels)
        bucket_nz = np.bincount(group, weights=p_nz.astype(np.float64),
                                minlength=levels)
        q = np.zeros(i, np.float64)
        safe = bucket_nz[group] > 0
        q[safe] = (bucket_sum[group] / np.maximum(bucket_nz[group], 1))[safe]
        q[~p_nz] = 0.0
        ps = p / p.sum()
        qs_total = q.sum()
        if qs_total <= 0:
            continue
        qs = q / qs_total
        mask = (ps > 0) & (qs > 0)
        if not mask.any():
            continue
        kl = float(np.sum(ps[mask] * np.log(ps[mask] / qs[mask])))
        # mass of p where q is zero is unrepresentable: penalize
        kl += float(ps[(ps > 0) & (qs <= 0)].sum()) * 10.0
        if kl < best_kl:
            best_kl, best_i = kl, i
    return best_i


class KLObserver(ObserverFactory):
    """Entropy (KL-divergence) calibrated scale (reference: the 'KL' algo
    of static PostTrainingQuantization)."""

    def __init__(self, quant_bits=8, bins=2048):
        super().__init__(quant_bits=quant_bits, bins=bins)
        self._cls = KLObserverLayer


class KLObserverLayer(BaseObserver):
    def __init__(self, quant_bits=8, bins=2048):
        super().__init__()
        self._quant_bits = quant_bits
        self._state = _HistogramState(bins)

    def forward(self, x):
        x = as_tensor(x)
        self._state.update(np.abs(np.asarray(x._value, np.float32)).ravel())
        return x

    def scales(self):
        st = self._state
        if st.amax is None:
            return Tensor(jnp.asarray(1.0), _internal=True)
        levels = 2 ** (self._quant_bits - 1)
        i = _kl_divergence_threshold(st.hist, levels)
        return Tensor(jnp.asarray(i * st.bin_width), _internal=True)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return -1


class AbsMaxChannelWiseWeightObserver(ObserverFactory):
    """Per-output-channel weight abs-max (reference:
    observers/abs_max.py AbsMaxChannelWiseWeightObserver)."""

    def __init__(self, quant_bits=8, quant_axis=-1):
        super().__init__(quant_bits=quant_bits, quant_axis=quant_axis)
        self._cls = AbsMaxChannelWiseWeightObserverLayer


class AbsMaxChannelWiseWeightObserverLayer(BaseObserver):
    def __init__(self, quant_bits=8, quant_axis=-1):
        super().__init__()
        self._quant_bits = quant_bits
        self._axis = quant_axis
        self._max = None

    def forward(self, w):
        w = as_tensor(w)
        v = jnp.abs(w._value.astype(jnp.float32))
        red = tuple(a for a in range(v.ndim)
                    if a != (self._axis % v.ndim))
        cur = jnp.max(v, axis=red)
        self._max = cur if self._max is None else jnp.maximum(self._max,
                                                              cur)
        return w

    def scales(self):
        if self._max is None:
            return Tensor(jnp.asarray(1.0), _internal=True)
        return Tensor(jnp.maximum(self._max, 1e-8), _internal=True)

    def fake_quant(self, w):
        """STE fake-quant with per-channel broadcast."""
        from .quanters import fake_quant as _fq
        w = as_tensor(w)
        s = self.scales()._value
        shape = [1] * w._value.ndim
        shape[self._axis % w._value.ndim] = -1
        return _fq(w, Tensor(s.reshape(shape), _internal=True),
                   self._quant_bits)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return self._axis


class GroupWiseWeightObserver(ObserverFactory):
    """Per-group weight abs-max for low-bit (int4) quantization
    (reference: observers/groupwise.py GroupWiseWeightObserver — groups
    of ``group_size`` along the input dim share one scale)."""

    def __init__(self, quant_bits=4, group_size=128):
        super().__init__(quant_bits=quant_bits, group_size=group_size)
        self._cls = GroupWiseWeightObserverLayer


class GroupWiseWeightObserverLayer(BaseObserver):
    def __init__(self, quant_bits=4, group_size=128):
        super().__init__()
        self._quant_bits = quant_bits
        self._group = group_size
        self._max = None

    def _group_absmax(self, v):
        """(in, out) -> (ceil(in/g), out) per-group abs-max."""
        din = v.shape[0]
        g = min(self._group, din)
        pad = (-din) % g
        if pad:
            v = jnp.concatenate(
                [v, jnp.zeros((pad,) + v.shape[1:], v.dtype)], 0)
        grouped = v.reshape((v.shape[0] // g, g) + v.shape[1:])
        return jnp.max(jnp.abs(grouped.astype(jnp.float32)), axis=1)

    def forward(self, w):
        w = as_tensor(w)
        cur = self._group_absmax(w._value)
        self._max = cur if self._max is None else jnp.maximum(self._max,
                                                              cur)
        return w

    def scales(self):
        if self._max is None:
            return Tensor(jnp.asarray(1.0), _internal=True)
        return Tensor(jnp.maximum(self._max, 1e-8), _internal=True)

    def fake_quant(self, w):
        from .quanters import fake_quant as _fq
        w = as_tensor(w)
        v = w._value
        din = v.shape[0]
        g = min(self._group, din)
        pad = (-din) % g
        s = self.scales()._value          # (G, *rest)
        vv = v
        if pad:
            vv = jnp.concatenate(
                [vv, jnp.zeros((pad,) + v.shape[1:], v.dtype)], 0)
        grouped = vv.reshape((vv.shape[0] // g, g) + vv.shape[1:])
        out = _fq(Tensor(grouped, _internal=True),
                  Tensor(s[:, None], _internal=True), self._quant_bits)
        flat = out._value.reshape((-1,) + v.shape[1:])[:din]
        return Tensor(flat, _internal=True)

    def bit_length(self):
        return self._quant_bits

    def quant_axis(self):
        return 0
