"""Dtype system for paddle_tpu.

Mirrors the reference's DataType enum (reference: paddle/phi/common/data_type.h)
as thin aliases over numpy/jax dtypes, plus default-dtype state
(reference: python/paddle/framework/framework.py set_default_dtype).
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp
import ml_dtypes

# Canonical dtype objects are numpy dtypes (jax uses the same).
bool_ = np.dtype("bool")
uint8 = np.dtype("uint8")
int8 = np.dtype("int8")
int16 = np.dtype("int16")
int32 = np.dtype("int32")
int64 = np.dtype("int64")
float16 = np.dtype("float16")
bfloat16 = np.dtype(ml_dtypes.bfloat16)
float32 = np.dtype("float32")
float64 = np.dtype("float64")
complex64 = np.dtype("complex64")
complex128 = np.dtype("complex128")
float8_e4m3fn = np.dtype(ml_dtypes.float8_e4m3fn)
float8_e5m2 = np.dtype(ml_dtypes.float8_e5m2)

_STR_ALIASES = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "half": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float": float32,
    "float64": float64,
    "double": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
}

_FLOATING = {float16, bfloat16, float32, float64, float8_e4m3fn, float8_e5m2}
_INTEGER = {uint8, int8, int16, int32, int64}
_COMPLEX = {complex64, complex128}

_default_dtype = float32


def set_default_dtype(d):
    """Set the global default float dtype (reference:
    python/paddle/framework/framework.py:set_default_dtype)."""
    global _default_dtype
    d = convert_dtype(d)
    if d not in (float16, bfloat16, float32, float64):
        raise TypeError(
            "set_default_dtype only supports float16/bfloat16/float32/float64, "
            f"got {d}")
    _default_dtype = d


def get_default_dtype():
    return _default_dtype


def _canonicalize(d):
    """Map 64-bit types to their 32-bit TPU-native counterparts unless jax
    x64 is enabled (TPU has no fast int64/float64 path; this mirrors jax's
    own default-x32 canonicalisation)."""
    import jax
    if jax.config.jax_enable_x64:
        return d
    return {np.dtype("int64"): int32, np.dtype("uint64"): np.dtype("uint32"),
            np.dtype("float64"): float32,
            np.dtype("complex128"): complex64}.get(d, d)


def convert_dtype(d, canonicalize=True):
    """Normalise any dtype spec (str, np.dtype, python type, jnp dtype) to a
    numpy dtype object."""
    if d is None:
        return None
    if isinstance(d, str):
        name = d.split(".")[-1]  # accept "paddle.float32" style
        out = _STR_ALIASES.get(name) or np.dtype(name)
    elif d is bool:
        out = bool_
    elif d is int:
        out = int64
    elif d is float:
        out = _default_dtype
    elif d is complex:
        out = complex64
    else:
        out = np.dtype(d)
    return _canonicalize(out) if canonicalize else out


def is_floating_point(d):
    return convert_dtype(d) in _FLOATING


def is_integer(d):
    return convert_dtype(d) in _INTEGER


def is_complex(d):
    return convert_dtype(d) in _COMPLEX


def is_bool(d):
    return convert_dtype(d) == bool_


def dtype_name(d):
    d = convert_dtype(d)
    return d.name


def promote_types(a, b):
    return np.dtype(jnp.promote_types(convert_dtype(a), convert_dtype(b)))


def finfo(d):
    return ml_dtypes.finfo(convert_dtype(d))


def iinfo(d):
    return np.iinfo(convert_dtype(d))
