"""Eager autograd engine on a functional substrate.

TPU-native re-design of the reference's eager autograd
(reference: paddle/fluid/eager/ — GradNodeBase grad_node_info.h:197,
RunBackward backward.cc:105, Backward backward.cc:439,
GradNodeAccumulation accumulation/).

Instead of generated per-op GradNode classes, every traced-through op records
one tape ``Node`` holding the ``jax.vjp`` closure of its primitive function.
``backward()`` walks the tape in reverse topological order, exactly like the
reference's BFS over GradNodeBase, and accumulates ``.grad`` on leaf tensors
(the reference's GradNodeAccumulation).

The tape is pure Python bookkeeping — it works identically on concrete
``jax.Array`` values (eager/dygraph mode) and on tracers (inside ``jax.jit``),
so the same imperative code is jit-able.
"""
from __future__ import annotations

import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp

from . import dtype as dtypes

_state = threading.local()


def is_grad_enabled() -> bool:
    return getattr(_state, "grad_enabled", True)


def _set_grad_enabled(v: bool) -> bool:
    old = is_grad_enabled()
    _state.grad_enabled = v
    return old


class no_grad:
    """Context manager / decorator disabling tape recording
    (reference: python/paddle/base/dygraph/base.py no_grad_)."""

    def __enter__(self):
        self._old = _set_grad_enabled(False)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._old)
        return False

    def __call__(self, fn):
        import functools

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with no_grad():
                return fn(*args, **kwargs)

        return wrapper


class enable_grad:
    def __enter__(self):
        self._old = _set_grad_enabled(True)
        return self

    def __exit__(self, *exc):
        _set_grad_enabled(self._old)
        return False


def set_grad_enabled(mode: bool):
    class _Guard:
        def __init__(self):
            self._old = _set_grad_enabled(mode)

        def __enter__(self):
            return self

        def __exit__(self, *exc):
            _set_grad_enabled(self._old)
            return False

    return _Guard()


class InputRef:
    """Edge of the tape graph: which tensor an input grad routes to, and the
    node that produced that tensor *at record time*. Snapshotting the
    producer here (instead of reading ``tensor._node`` at backward time)
    makes in-place rebinding of tensors safe: the graph is over value
    history, not object identity. jax arrays are immutable, so saved
    activations can never be corrupted by in-place ops — unlike the
    reference, which needs an inplace-version guard
    (paddle/fluid/eager/utils.h)."""

    __slots__ = ("tensor", "node", "out_index")

    def __init__(self, tensor):
        self.tensor = tensor
        self.node = tensor._node
        self.out_index = tensor._out_index


class Node:
    """One recorded op on the tape (analog of GradNodeBase,
    reference: paddle/fluid/eager/grad_node_info.h:197).

    ``fn``/``raw``/``diff_idx`` (set by :func:`apply`) let ``backward(...,
    create_graph=True)`` re-trace the VJP *as a recorded op* so the
    gradient computation itself lands on the tape — the TPU-native analog
    of the reference's double-grad GradNodes (generated
    ``*_double_grad`` kernels, eager_gen.py higher-order branches).
    ``vjp_graph_fn`` is the PyLayer override (runs the user backward in
    grad mode)."""

    __slots__ = ("vjp_fn", "inputs", "out_meta", "out_is_seq", "name",
                 "fn", "raw", "diff_idx", "vjp_graph_fn", "__weakref__")

    def __init__(self, vjp_fn, inputs, out_meta, out_is_seq, name=""):
        self.vjp_fn = vjp_fn
        self.inputs = [InputRef(t) for t in inputs]
        self.out_meta = out_meta  # list of (shape, dtype) per differentiable output
        self.out_is_seq = out_is_seq  # fn returned a tuple/list (cotangent structure)
        self.name = name
        self.fn = None
        self.raw = None
        self.diff_idx = None
        self.vjp_graph_fn = None


def _is_diff_dtype(d) -> bool:
    return dtypes.is_floating_point(d) or dtypes.is_complex(d)


# AMP autocast hook, installed by paddle_tpu.amp (avoids an import cycle);
# signature: (op_name, raw_values) -> raw_values
# (reference: AMP branch generated into every ad_func,
# paddle/fluid/eager/amp_auto_cast.h)
_amp_hook = [None]


def set_amp_hook(fn):
    _amp_hook[0] = fn


# static-mode program recorder (paddle_tpu.static): called with
# (fn, args, outs) for every apply so Executor.run can replay the op
# sequence with fed placeholder values
_static_hook = [None]


def set_static_hook(fn):
    _static_hook[0] = fn


def apply(fn: Callable, *args, name: str = "", multi_out: bool = False,
          nondiff: tuple = ()):
    """Run primitive ``fn`` over raw values of ``args`` and record a tape node.

    ``args`` may mix Tensors and raw values; only float/complex Tensors with
    ``stop_gradient=False`` are differentiated. ``nondiff`` lists arg
    positions excluded from differentiation regardless of dtype/flags
    (e.g. soft labels — the reference's grad kernels never emit label
    grads). Returns Tensor (or tuple of Tensors if ``fn`` returns a
    tuple/list or ``multi_out``).
    """
    from .tensor import Tensor  # local import to break the cycle

    raw: List[Any] = []
    tensors: List[Tuple[int, Tensor]] = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            raw.append(a._value)
            tensors.append((i, a))
        else:
            raw.append(a)

    if _amp_hook[0] is not None:
        raw = _amp_hook[0](name or getattr(fn, "__name__", ""), raw)

    track = is_grad_enabled() and any(
        (not t.stop_gradient) and _is_diff_dtype(t.dtype)
        and i not in nondiff for i, t in tensors)

    if not track:
        out = fn(*raw)
        wrapped = _wrap_outputs(out, node=None, stop_gradient=True,
                                multi_out=multi_out)
        if _static_hook[0] is not None:
            _static_hook[0](fn, args, wrapped)
        return wrapped

    diff = [(i, t) for i, t in tensors
            if (not t.stop_gradient) and _is_diff_dtype(t.dtype)
            and i not in nondiff]
    diff_idx = [i for i, _ in diff]
    diff_tensors = [t for _, t in diff]

    def f(*diff_vals):
        vals = list(raw)
        for j, i in enumerate(diff_idx):
            vals[i] = diff_vals[j]
        return fn(*vals)

    out_vals, vjp_fn = jax.vjp(f, *[raw[i] for i in diff_idx])

    is_seq = isinstance(out_vals, (tuple, list))
    flat_outs = list(out_vals) if is_seq else [out_vals]
    out_meta = [(tuple(o.shape), jnp.result_type(o)) for o in flat_outs]
    node = Node(vjp_fn, diff_tensors, out_meta, is_seq,
                name=name or getattr(fn, "__name__", "op"))
    # retained for create_graph=True VJP re-tracing.  Differentiable
    # positions are nulled out: InputRef already pins those tensors and the
    # re-trace overwrites them with live primals, so the only extra
    # retention is non-diff inputs (indices/masks/scalars — typically tiny
    # or already pinned as vjp residuals).
    node.fn = fn
    node.raw = [None if i in diff_idx else v for i, v in enumerate(raw)]
    node.diff_idx = diff_idx

    outs = []
    for k, o in enumerate(flat_outs):
        sg = not _is_diff_dtype(jnp.result_type(o))
        t = Tensor(o, stop_gradient=sg, _internal=True)
        if not sg:
            t._node = node
            t._out_index = k
        outs.append(t)
    result = tuple(outs) if (is_seq or multi_out) else outs[0]
    if _static_hook[0] is not None:
        _static_hook[0](fn, args, result)
    return result


def _wrap_outputs(out, node, stop_gradient, multi_out):
    from .tensor import Tensor
    if isinstance(out, (tuple, list)):
        return tuple(Tensor(o, stop_gradient=stop_gradient, _internal=True)
                     for o in out)
    t = Tensor(out, stop_gradient=stop_gradient, _internal=True)
    return (t,) if multi_out else t


def backward(tensors, grad_tensors=None, retain_graph=False, grad_sink=None,
             create_graph=False):
    """Run reverse accumulation from ``tensors``
    (reference: egr::Backward paddle/fluid/eager/backward.cc:439,
    RunBackward backward.cc:105).

    ``grad_sink``: if given (a dict), leaf gradients are accumulated into
    ``grad_sink[id(tensor)]`` instead of ``tensor.grad`` — used by the
    functional :func:`grad` API so it never mutates ``.grad`` state.

    ``create_graph``: cotangents are carried as *Tensors* and every VJP is
    re-traced through :func:`apply`, so the computed gradients are
    themselves on the tape and can be differentiated again (reference:
    double-grad GradNodes / ``paddle.grad(create_graph=True)``).  Mutating
    an input in place (``_inplace_assign``) between the forward and a
    ``create_graph`` backward yields the mutated primal, like the
    reference's inplace-version guard would reject; run backward first.
    """
    from .tensor import Tensor

    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    elif isinstance(grad_tensors, Tensor):
        grad_tensors = [grad_tensors]
    if len(grad_tensors) != len(tensors):
        raise ValueError(
            f"backward: got {len(tensors)} tensors but {len(grad_tensors)} "
            "grad_tensors")

    # node -> list of accumulated output cotangents
    pending: dict = {}
    roots: List[Node] = []

    for t, g in zip(tensors, grad_tensors):
        if t.stop_gradient:
            raise RuntimeError(
                "backward() called on a tensor with stop_gradient=True")
        if g is None:
            if t._value.size != 1:
                raise RuntimeError(
                    "grad must be provided for non-scalar backward root "
                    f"(shape={t.shape})")
            gval = jnp.ones_like(t._value)
            if create_graph:
                gval = Tensor(gval, stop_gradient=True, _internal=True)
        elif create_graph:
            # keep the Tensor: grad-of-grad w.r.t. grad_outputs must flow
            gval = g if isinstance(g, Tensor) else Tensor(
                jnp.asarray(g), stop_gradient=True, _internal=True)
        else:
            gval = g._value if isinstance(g, Tensor) else jnp.asarray(g)
        node = t._node
        if node is None:
            _accumulate_leaf(t, gval, grad_sink)
            continue
        slot = pending.setdefault(id(node), [node, [None] * len(node.out_meta)])
        k = t._out_index
        slot[1][k] = gval if slot[1][k] is None else slot[1][k] + gval
        roots.append(node)

    # topological order via iterative DFS over node graph
    order: List[Node] = []
    seen = set()
    stack = [(n, False) for n in roots]
    while stack:
        node, processed = stack.pop()
        if processed:
            order.append(node)
            continue
        if id(node) in seen:
            continue
        seen.add(id(node))
        stack.append((node, True))
        for ref in node.inputs:
            if ref.node is not None and id(ref.node) not in seen:
                stack.append((ref.node, False))

    # reverse topological = order reversed (DFS postorder gives children first)
    for node in reversed(order):
        slot = pending.get(id(node))
        if slot is None:
            continue
        if create_graph:
            out_grads = [
                g if g is not None else Tensor(jnp.zeros(shape, dtype),
                                               stop_gradient=True,
                                               _internal=True)
                for g, (shape, dtype) in zip(slot[1], node.out_meta)
            ]
        else:
            out_grads = [
                g if g is not None else jnp.zeros(shape, dtype)
                for g, (shape, dtype) in zip(slot[1], node.out_meta)
            ]
        if node.vjp_fn is None:
            raise RuntimeError(
                "trying to backward through the graph a second time: "
                "set retain_graph=True on the first backward() call")
        if create_graph:
            in_grads = _node_vjp_graph(node, out_grads)
        else:
            in_grads = node.vjp_fn(tuple(out_grads) if node.out_is_seq
                                   else out_grads[0])
        for ref, g in zip(node.inputs, in_grads):
            t = ref.tensor
            for hook in t._grad_hooks:
                h = hook(g if isinstance(g, Tensor)
                         else Tensor(g, stop_gradient=True, _internal=True))
                if h is not None:
                    if create_graph:
                        g = h if isinstance(h, Tensor) else Tensor(
                            jnp.asarray(h), stop_gradient=True,
                            _internal=True)
                    else:
                        g = h._value if isinstance(h, Tensor) else h
            if ref.node is None or t._retain_grads:
                _accumulate_leaf(t, g, grad_sink)
            if ref.node is not None:
                s = pending.setdefault(
                    id(ref.node), [ref.node, [None] * len(ref.node.out_meta)])
                k = ref.out_index
                s[1][k] = g if s[1][k] is None else s[1][k] + g
        if not retain_graph and not create_graph:
            # NOT freed under create_graph: the re-traced grad graph's
            # nodes reference original nodes through their primal-input
            # InputRefs (a later backward over the grad graph routes
            # cotangents — zero for linear ops, nonzero otherwise —
            # through them), so create_graph structurally implies
            # retain_graph (same coupling as the reference/torch)
            node.vjp_fn = None
            node.fn = None       # free re-trace closures with the residuals
            node.raw = None
        del pending[id(node)]


def _node_vjp_graph(node: Node, out_grads):
    """Run ``node``'s VJP as a *recorded* op so the result carries a tape
    (the create_graph=True engine).  Builtin ops re-trace ``jax.vjp`` of
    the saved primitive over (primal inputs, cotangents); PyLayer nodes
    run their user backward in grad mode (``vjp_graph_fn``)."""
    from .tensor import Tensor

    cots = [g if isinstance(g, Tensor)
            else Tensor(g, stop_gradient=True, _internal=True)
            for g in out_grads]
    if node.vjp_graph_fn is not None:
        return node.vjp_graph_fn(cots)
    if node.fn is None:
        raise RuntimeError(
            f"op '{node.name}' does not support create_graph=True "
            "(no primitive recorded for VJP re-tracing)")
    fn, raw, diff_idx = node.fn, node.raw, node.diff_idx
    n_in = len(diff_idx)
    is_seq = node.out_is_seq

    def vjp_op(*vals):
        prim, cv = vals[:n_in], vals[n_in:]

        def f(*dv):
            vs = list(raw)
            for j, i in enumerate(diff_idx):
                vs[i] = dv[j]
            return fn(*vs)

        _, vf = jax.vjp(f, *prim)
        return tuple(vf(tuple(cv) if is_seq else cv[0]))

    outs = apply(vjp_op, *[r.tensor for r in node.inputs], *cots,
                 name=(node.name or "op") + "_grad", multi_out=True)
    return list(outs)


def _accumulate_leaf(t, gval, grad_sink=None):
    from .tensor import Tensor
    if grad_sink is not None:
        prev = grad_sink.get(id(t))
        grad_sink[id(t)] = gval if prev is None else prev + gval
        return
    if isinstance(gval, Tensor):
        # create_graph mode: .grad keeps its tape so it can be
        # differentiated again (reference double-grad semantics)
        t._grad = gval if t.grad is None else t._grad + gval
        return
    if t.grad is None:
        t._grad = Tensor(gval, stop_gradient=True, _internal=True)
    else:
        t._grad = Tensor(t._grad._value + gval, stop_gradient=True,
                         _internal=True)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False):
    """Functional gradient API (reference: python/paddle/autograd/autograd.py
    ``paddle.grad``). Computes grads of outputs w.r.t. inputs without touching
    ``.grad`` of any tensor (gradients flow into a side sink)."""
    from .tensor import Tensor

    if isinstance(outputs, Tensor):
        outputs = [outputs]
    if isinstance(inputs, Tensor):
        inputs = [inputs]
    if create_graph and retain_graph is not None and not retain_graph:
        raise ValueError(
            "retain_graph=False is incompatible with create_graph=True: "
            "the re-traced gradient graph references the original graph's "
            "nodes, so it cannot be freed")
    if retain_graph is None:
        retain_graph = create_graph

    saved_retain = [(t, t._retain_grads) for t in inputs]
    sink: dict = {}
    for t in inputs:
        t._retain_grads = True  # ensure non-leaf inputs receive grads
    try:
        if create_graph:
            with enable_grad():
                backward(outputs, grad_tensors=grad_outputs,
                         retain_graph=True, grad_sink=sink,
                         create_graph=True)
        else:
            backward(outputs, grad_tensors=grad_outputs,
                     retain_graph=bool(retain_graph), grad_sink=sink)
        res = []
        for t in inputs:
            g = sink.get(id(t))
            if g is None:
                if not allow_unused:
                    raise RuntimeError(
                        "one of the inputs was not used in the graph; pass "
                        "allow_unused=True to return None for it")
                res.append(None)
            elif isinstance(g, Tensor):
                # create_graph mode: the grad carries its own tape
                res.append(g)
            else:
                res.append(Tensor(g, stop_gradient=True, _internal=True))
        return res
    finally:
        for t, r in saved_retain:
            t._retain_grads = r
