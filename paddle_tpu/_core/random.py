"""RNG state management on a stateless-PRNG substrate.

The reference keeps mutable per-device generator state
(reference: paddle/phi/core/generator.h, python/paddle/framework/random.py
``paddle.seed``). JAX PRNG is stateless, so the imperative surface keeps a
global ``Generator`` whose key is split on every draw (eager parity), while
jit-compiled code paths use an explicit *rng scope*: the training-step wrapper
threads a fresh traced key per step and ops derive per-call-site streams via
``fold_in`` with a static counter. This mirrors the determinism contract of
the reference's ``RNGStatesTracker``
(python/paddle/distributed/fleet/layers/mpu/random.py:34) without stateful
device RNG.
"""
from __future__ import annotations

import threading
from typing import Optional

import numpy as np
import jax

_state = threading.local()


class Generator:
    """Stateful key-splitting generator (reference: phi::Generator)."""

    def __init__(self, seed: int = 0):
        self._seed = seed
        self._key = None  # lazily created — constructing a key initializes
        self._offset = 0  # the JAX backend, which must not happen at import

    @property
    def key(self):
        if self._key is None:
            self._key = jax.random.key(self._seed)
        return self._key

    def manual_seed(self, seed: int):
        self._seed = seed
        self._key = None
        self._offset = 0
        return self

    def initial_seed(self) -> int:
        return self._seed

    def get_state(self):
        return (self._seed, self._offset,
                np.asarray(jax.random.key_data(self.key)))

    def set_state(self, state):
        self._seed, self._offset, key_data = state
        self._key = jax.random.wrap_key_data(
            jax.numpy.asarray(key_data))

    def next_key(self):
        self._key, sub = jax.random.split(self.key)
        self._offset += 1
        return sub


# Created on first use, never at import: ``import paddle_tpu`` must not
# initialize the JAX backend (a hung device tunnel would poison every entry
# point otherwise).
_default_generator: Optional[Generator] = None


def default_generator() -> Generator:
    global _default_generator
    if _default_generator is None:
        _default_generator = Generator(np.random.randint(0, 2**31 - 1))
    return _default_generator


def seed(s: int):
    """reference: python/paddle/framework/random.py ``paddle.seed``."""
    return default_generator().manual_seed(int(s))


def get_rng_state():
    return [default_generator().get_state()]


def set_rng_state(state):
    default_generator().set_state(state[0])


class rng_scope:
    """Bind an explicit (possibly traced) PRNG key for random ops in scope.

    Inside the scope every random op draws ``fold_in(key, counter)`` where
    ``counter`` is a static per-call sequence number — deterministic given the
    key, jit-safe, and unique per call site in a traced program.
    """

    def __init__(self, key):
        self.key = key

    def __enter__(self):
        self._old = getattr(_state, "scope", None)
        _state.scope = [self.key, 0]
        return self

    def __exit__(self, *exc):
        _state.scope = self._old
        return False


def next_rng_key():
    """Get the next PRNG key: from the active scope if any, else the global
    generator."""
    scope = getattr(_state, "scope", None)
    if scope is not None:
        key, ctr = scope
        scope[1] = ctr + 1
        return jax.random.fold_in(key, ctr)
    return default_generator().next_key()


def in_rng_scope() -> bool:
    return getattr(_state, "scope", None) is not None


class use_generator:
    """Temporarily route random draws to ``gen`` (the hook RNGStatesTracker
    uses to give each model-parallel stream its own generator — reference:
    python/paddle/distributed/fleet/layers/mpu/random.py:34)."""

    def __init__(self, gen: Generator):
        self._gen = gen

    def __enter__(self):
        global _default_generator
        self._old = default_generator()
        _default_generator = self._gen
        return self._gen

    def __exit__(self, *exc):
        global _default_generator
        _default_generator = self._old
        return False
