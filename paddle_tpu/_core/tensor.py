"""Tensor: imperative wrapper over ``jax.Array``.

TPU-native analog of the reference's eager Tensor
(reference: paddle/phi/api/include/tensor.h:82 paddle::Tensor;
python surface python/paddle/base/dygraph/tensor_patch_methods.py).

Design: a ``Tensor`` owns a ``jax.Array`` (or tracer) in ``_value`` plus
autograd bookkeeping (``stop_gradient``, ``.grad``, tape node). In-place ops
rebind ``_value`` and bump ``_version`` (the reference's inplace_version
counter, paddle/fluid/eager/utils.h) so the tape can detect illegal
mutation of saved activations. Tensors are registered as a jax pytree node,
so they flow through ``jax.jit`` / ``jax.tree_util`` transparently.
"""
from __future__ import annotations

from typing import Any, Optional

import numpy as np
import jax
import jax.numpy as jnp

from . import autograd
from . import dtype as dtypes


def _to_jax(data, dtype=None):
    if isinstance(data, Tensor):
        data = data._value
    if isinstance(data, (jax.Array,)) or hasattr(data, "aval"):
        return data if dtype is None else data.astype(dtypes.convert_dtype(dtype))
    arr = np.asarray(data)
    if dtype is not None:
        arr = arr.astype(dtypes.convert_dtype(dtype))
    elif arr.dtype == np.float64:
        arr = arr.astype(dtypes.get_default_dtype())
    elif arr.dtype == np.int32:
        pass
    return jnp.asarray(arr)


_tensor_counter = [0]


# static-mode rebinding recorder (paddle_tpu.static): in-place ops rebind
# an existing Tensor to a new value; the program replay needs those "bind"
# events to route fed values through aliases
_inplace_hook = [None]


def set_inplace_hook(fn):
    _inplace_hook[0] = fn


class Tensor:
    __slots__ = ("_value", "_stop_gradient", "_grad", "_node", "_out_index",
                 "_version", "_retain_grads", "_grad_hooks", "name",
                 "persistable", "__weakref__", "__dict__")

    def __init__(self, data=None, dtype=None, place=None, stop_gradient=True,
                 name: Optional[str] = None, _internal: bool = False):
        if _internal:
            self._value = data
        else:
            self._value = _to_jax(data, dtype)
        self._stop_gradient = stop_gradient
        self._grad = None
        self._node = None
        self._out_index = 0
        self._version = 0
        self._retain_grads = False
        self._grad_hooks = []
        self.persistable = False
        if name is None:
            _tensor_counter[0] += 1
            name = f"generated_tensor_{_tensor_counter[0]}"
        self.name = name

    # ---- basic properties ----
    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    dim = ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(jnp.result_type(self._value))

    @property
    def stop_gradient(self):
        return self._stop_gradient

    @stop_gradient.setter
    def stop_gradient(self, v):
        self._stop_gradient = bool(v)

    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g if (g is None or isinstance(g, Tensor)) else Tensor(g)

    @property
    def is_leaf(self):
        return self._node is None

    @property
    def place(self):
        try:
            devs = self._value.devices()
            return next(iter(devs))
        except Exception:
            return jax.devices()[0]

    @property
    def T(self):
        return autograd.apply(lambda x: jnp.swapaxes(x, -1, -2)
                              if x.ndim >= 2 else x, self, name="t")

    @property
    def mT(self):
        return self.T

    @property
    def inplace_version(self):
        return self._version

    # ---- conversion ----
    def numpy(self):
        return np.asarray(self._value)

    def item(self, *args):
        if args:
            return self.numpy().item(*args)
        return self.numpy().item()

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __jax_array__(self):
        return self._value

    def astype(self, dtype):
        d = dtypes.convert_dtype(dtype)
        return autograd.apply(lambda x: x.astype(d), self, name="cast")

    cast = astype

    def to(self, *args, **kwargs):
        t = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, (str, np.dtype)) and str(a).split(":")[0] not in (
                    "cpu", "gpu", "tpu", "xpu"):
                t = t.astype(a)
        return t

    def cpu(self):
        v = jax.device_put(self._value, jax.devices("cpu")[0]) \
            if jax.devices()[0].platform != "cpu" else self._value
        return Tensor(v, stop_gradient=self._stop_gradient, _internal=True)

    def cuda(self, *a, **k):  # parity alias: accelerator placement
        return Tensor(jax.device_put(self._value, jax.devices()[0]),
                      stop_gradient=self._stop_gradient, _internal=True)

    def pin_memory(self):
        return self

    # ---- autograd surface ----
    def backward(self, grad_tensor=None, retain_graph=False):
        autograd.backward([self], None if grad_tensor is None else [grad_tensor],
                          retain_graph=retain_graph)

    def clear_grad(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._value),
                                stop_gradient=True, _internal=True)
        else:
            self._grad = None

    clear_gradient = clear_grad

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, _internal=True)
        return t

    def detach_(self):
        self._node = None
        self._stop_gradient = True
        return self

    def clone(self):
        return autograd.apply(lambda x: x + 0, self, name="clone")

    def register_hook(self, hook):
        self._grad_hooks.append(hook)

        class _Handle:
            def remove(h):
                try:
                    self._grad_hooks.remove(hook)
                except ValueError:
                    pass
        return _Handle()

    def retain_grads(self):
        self._retain_grads = True

    # ---- in-place machinery ----
    def _inplace_assign(self, new_value, node=None, out_index=0):
        old = self._value if _inplace_hook[0] is not None else None
        self._value = new_value
        self._version += 1
        self._node = node
        self._out_index = out_index
        if _inplace_hook[0] is not None:
            _inplace_hook[0](self, None, new_value, old)

    def _inplace_from(self, t: "Tensor"):
        old = self._value if _inplace_hook[0] is not None else None
        self._value = t._value
        self._version += 1
        self._node = t._node
        self._out_index = t._out_index
        if _inplace_hook[0] is not None:
            _inplace_hook[0](self, t, None, old)
        if t._node is not None:
            # e.g. buf[i] = net_out where buf had stop_gradient=True: the
            # result now depends on a differentiable input, so it must track
            self._stop_gradient = False
        return self

    def copy_(self, other, blocking=True):
        o = other._value if isinstance(other, Tensor) else jnp.asarray(other)
        self._inplace_assign(o.astype(self.dtype))
        return self

    def set_value(self, value):
        return self.copy_(value)

    def fill_(self, value):
        self._inplace_assign(jnp.full_like(self._value, value))
        return self

    def zero_(self):
        self._inplace_assign(jnp.zeros_like(self._value))
        return self

    # ---- indexing ----
    def __getitem__(self, idx):
        idx = _index_to_raw(idx)
        return autograd.apply(lambda x: x[idx], self, name="getitem")

    def __setitem__(self, idx, value):
        idx = _index_to_raw(idx)
        if isinstance(value, Tensor):
            out = autograd.apply(
                lambda x, val: x.at[idx].set(val.astype(x.dtype)
                                             if hasattr(val, "astype") else val),
                self, value, name="setitem")
        else:
            out = autograd.apply(lambda x: x.at[idx].set(value), self,
                                 name="setitem")
        self._inplace_from(out)

    def __len__(self):
        if self.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    # ---- operators ----
    def _binary(self, other, fn, name, reverse=False):
        if isinstance(other, (list, tuple, np.ndarray)):
            other = Tensor(other)
        if reverse:
            return autograd.apply(lambda y, x: fn(x, y), self, other, name=name)
        return autograd.apply(fn, self, other, name=name)

    def __add__(self, o):
        return self._binary(o, jnp.add, "add")
    __radd__ = __add__

    def __sub__(self, o):
        return self._binary(o, jnp.subtract, "subtract")

    def __rsub__(self, o):
        return self._binary(o, jnp.subtract, "subtract", reverse=True)

    def __mul__(self, o):
        return self._binary(o, jnp.multiply, "multiply")
    __rmul__ = __mul__

    def __truediv__(self, o):
        return self._binary(o, jnp.true_divide, "divide")

    def __rtruediv__(self, o):
        return self._binary(o, jnp.true_divide, "divide", reverse=True)

    def __floordiv__(self, o):
        return self._binary(o, jnp.floor_divide, "floor_divide")

    def __rfloordiv__(self, o):
        return self._binary(o, jnp.floor_divide, "floor_divide", reverse=True)

    def __mod__(self, o):
        return self._binary(o, jnp.remainder, "remainder")

    def __rmod__(self, o):
        return self._binary(o, jnp.remainder, "remainder", reverse=True)

    def __pow__(self, o):
        return self._binary(o, jnp.power, "pow")

    def __rpow__(self, o):
        return self._binary(o, jnp.power, "pow", reverse=True)

    def __matmul__(self, o):
        return self._binary(o, jnp.matmul, "matmul")

    def __rmatmul__(self, o):
        return self._binary(o, jnp.matmul, "matmul", reverse=True)

    def __neg__(self):
        return autograd.apply(jnp.negative, self, name="neg")

    def __abs__(self):
        return autograd.apply(jnp.abs, self, name="abs")

    def __invert__(self):
        return autograd.apply(jnp.logical_not, self, name="logical_not")

    # comparisons (outputs bool -> stop_gradient)
    def __eq__(self, o):
        return self._binary(o, lambda a, b: a == b, "equal")

    def __ne__(self, o):
        return self._binary(o, lambda a, b: a != b, "not_equal")

    def __lt__(self, o):
        return self._binary(o, lambda a, b: a < b, "less_than")

    def __le__(self, o):
        return self._binary(o, lambda a, b: a <= b, "less_equal")

    def __gt__(self, o):
        return self._binary(o, lambda a, b: a > b, "greater_than")

    def __ge__(self, o):
        return self._binary(o, lambda a, b: a >= b, "greater_equal")

    def __and__(self, o):
        return self._binary(o, jnp.logical_and, "logical_and")

    def __or__(self, o):
        return self._binary(o, jnp.logical_or, "logical_or")

    def __xor__(self, o):
        return self._binary(o, jnp.logical_xor, "logical_xor")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        return bool(self.numpy())

    def __int__(self):
        return int(self.numpy())

    def __float__(self):
        return float(self.numpy())

    def __repr__(self):
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, precision=8, separator=", ")
        except Exception:
            body = repr(self._value)  # tracer
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"stop_gradient={self._stop_gradient},\n       {body})")

    # in-place arithmetic (API parity: trailing underscore)
    def add_(self, o):
        return self._inplace_from(self.__add__(o))

    def subtract_(self, o):
        return self._inplace_from(self.__sub__(o))

    def multiply_(self, o):
        return self._inplace_from(self.__mul__(o))

    def scale_(self, scale=1.0, bias=0.0):
        return self._inplace_from(autograd.apply(
            lambda x: x * scale + bias, self, name="scale"))

    def clip_(self, min=None, max=None):
        return self._inplace_from(autograd.apply(
            lambda x: jnp.clip(x, min, max), self, name="clip"))


def _index_to_raw(idx):
    if isinstance(idx, Tensor):
        return idx._value
    if isinstance(idx, tuple):
        return tuple(i._value if isinstance(i, Tensor) else i for i in idx)
    if isinstance(idx, list):
        return jnp.asarray(np.asarray(idx))
    return idx


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py
    EagerParamBase)."""

    def __init__(self, data=None, dtype=None, name=None, trainable=True,
                 _internal=False, **kwargs):
        super().__init__(data, dtype=dtype, name=name, stop_gradient=not trainable,
                         _internal=_internal)
        self.persistable = True
        self.optimize_attr = kwargs.get("optimize_attr", {"learning_rate": 1.0})
        self.regularizer = kwargs.get("regularizer", None)
        self.need_clip = kwargs.get("need_clip", True)
        self.is_distributed = False

    @property
    def trainable(self):
        return not self.stop_gradient

    @trainable.setter
    def trainable(self, v):
        self.stop_gradient = not v

    def __repr__(self):
        return "Parameter containing:\n" + super().__repr__()


# ---- pytree registration: Tensors flow through jax transforms ----
def _tensor_flatten(t: Tensor):
    return (t._value,), (type(t), t._stop_gradient)


def _tensor_unflatten(aux, children):
    cls, sg = aux
    if cls is Parameter:
        t = Parameter.__new__(Parameter)
        Tensor.__init__(t, children[0], stop_gradient=sg, _internal=True)
        t.persistable = True
        t.optimize_attr = {"learning_rate": 1.0}
        t.regularizer = None
        t.need_clip = True
        t.is_distributed = False
        return t
    return cls(children[0], stop_gradient=sg, _internal=True)


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(Parameter, _tensor_flatten, _tensor_unflatten)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """reference: python/paddle/tensor/creation.py to_tensor."""
    if isinstance(data, Tensor) and dtype is None:
        return Tensor(data._value, stop_gradient=stop_gradient, _internal=True)
    return Tensor(data, dtype=dtype, place=place, stop_gradient=stop_gradient)
