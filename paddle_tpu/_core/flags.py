"""Global runtime flag registry.

TPU-native analog of the reference's gflags-backed flag layer
(reference: paddle/common/flags.h:38, paddle/common/flags.cc ~190 flags;
python surface python/paddle/base/framework.py:132 set_flags / :157 get_flags).

Flags are declared in-process, override-able from the environment as
``FLAGS_<name>`` at first access, and settable via :func:`set_flags`.
"""
from __future__ import annotations

import os
import threading
from typing import Any, Callable, Dict, Optional

_lock = threading.Lock()


class _Flag:
    __slots__ = ("name", "value", "default", "type", "help", "env_read")

    def __init__(self, name: str, default: Any, type_: type, help_: str):
        self.name = name
        self.default = default
        self.value = default
        self.type = type_
        self.help = help_
        self.env_read = False


_REGISTRY: Dict[str, _Flag] = {}
_OBSERVERS: Dict[str, Callable[[Any], None]] = {}


def _coerce(flag: _Flag, value: Any) -> Any:
    if flag.type is bool and isinstance(value, str):
        return value.lower() in ("1", "true", "yes", "on")
    return flag.type(value)


def define_flag(name: str, default: Any, help_: str = "",
                type_: Optional[type] = None) -> None:
    """Declare a flag (analog of PHI_DEFINE_EXPORTED_*)."""
    with _lock:
        if name in _REGISTRY:
            return
        _REGISTRY[name] = _Flag(name, default,
                                type_ or (type(default) if default is not None else str),
                                help_)


def _flag(name: str) -> _Flag:
    if name.startswith("FLAGS_"):
        name = name[len("FLAGS_"):]
    if name not in _REGISTRY:
        raise KeyError(f"unknown flag: {name!r}")
    f = _REGISTRY[name]
    if not f.env_read:
        env = os.environ.get("FLAGS_" + f.name)
        if env is not None:
            f.value = _coerce(f, env)
        f.env_read = True
    return f


def get_flags(names):
    """Read one or more flags (reference: base/framework.py:157)."""
    single = isinstance(names, str)
    if single:
        names = [names]
    out = {}
    for n in names:
        f = _flag(n)
        out["FLAGS_" + f.name] = f.value
    return out


def set_flags(flags: Dict[str, Any]) -> None:
    """Set flags from a dict (reference: base/framework.py:132)."""
    for name, value in flags.items():
        f = _flag(name)
        f.env_read = True
        f.value = _coerce(f, value)
        obs = _OBSERVERS.get(f.name)
        if obs is not None:
            obs(f.value)


def on_flag_change(name: str, fn: Callable[[Any], None]) -> None:
    _OBSERVERS[name] = fn


def flag_value(name: str):
    return _flag(name).value


def all_flags() -> Dict[str, Any]:
    return {"FLAGS_" + k: _flag(k).value for k in _REGISTRY}


# ---- core flags (subset of reference paddle/common/flags.cc) ----
define_flag("check_nan_inf", False, "check outputs of every op for nan/inf")
define_flag("check_nan_inf_level", 0, "0: error on nan/inf; >0 log only")
define_flag("benchmark", False, "sync after op for stable timing")
define_flag("eager_delete_tensor_gb", 0.0, "GC threshold (no-op on TPU; XLA owns memory)")
define_flag("use_stride_kernel", True, "allow view/stride ops to alias (JAX always copies under the hood)")
define_flag("tpu_matmul_precision", "default", "jax matmul precision: default|high|highest")
define_flag("log_level", 0, "VLOG verbosity for paddle_tpu internals")
define_flag("allocator_strategy", "auto_growth", "kept for API parity; XLA owns device memory")
define_flag("embedding_deterministic", 0, "deterministic embedding grad (no-op: XLA scatter is deterministic)")
define_flag("cudnn_deterministic", False, "kept for parity; TPU is deterministic by default")
