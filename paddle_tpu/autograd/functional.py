"""Functional autodiff APIs (reference: python/paddle/autograd/autograd.py
jacobian/hessian; python/paddle/incubate/autograd vjp/jvp). On the jax
substrate these delegate to jax.jacobian/jax.hessian for exactness."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core.autograd import grad as _tape_grad


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """reference: paddle.grad (python/paddle/base/dygraph/base.py grad)."""
    return _tape_grad(outputs, inputs, grad_outputs, retain_graph,
                      create_graph, only_inputs, allow_unused)


def _wrap_fn(func):
    def raw_fn(*vals):
        ts = [Tensor(v, stop_gradient=False, _internal=True) for v in vals]
        out = func(*ts)
        if isinstance(out, (tuple, list)):
            return tuple(o._value if isinstance(o, Tensor) else o
                         for o in out)
        return out._value if isinstance(out, Tensor) else out
    return raw_fn


def jacobian(ys, xs, batch_axis=None):
    """reference: autograd/autograd.py jacobian — here func-form:
    jacobian(func, xs) or tensor-form handled via tape."""
    if callable(ys):
        func = _wrap_fn(ys)
        single = isinstance(xs, Tensor)
        vals = [xs._value] if single else [x._value for x in xs]
        jac = jax.jacobian(func, argnums=tuple(range(len(vals))))(*vals)
        if single:
            jac = jac[0]
            return Tensor(jac, _internal=True)
        return [Tensor(j, _internal=True) for j in jac]
    # tensor form: ys computed from xs on the tape — use vjp rows
    single_x = isinstance(xs, Tensor)
    xs_l = [xs] if single_x else list(xs)
    y = ys if isinstance(ys, Tensor) else ys[0]
    yv = y._value.reshape(-1)
    rows = []
    for i in range(yv.shape[0]):
        seed = jnp.zeros_like(yv).at[i].set(1.0).reshape(y._value.shape)
        gs = _tape_grad([y], xs_l,
                        grad_outputs=[Tensor(seed, _internal=True)],
                        retain_graph=True, allow_unused=True)
        rows.append([g._value.reshape(-1) if g is not None else
                     jnp.zeros(x.size) for g, x in zip(gs, xs_l)])
    jacs = []
    for j, x in enumerate(xs_l):
        m = jnp.stack([r[j] for r in rows], 0)
        jacs.append(Tensor(m.reshape(tuple(y.shape) + tuple(x.shape)),
                           _internal=True))
    return jacs[0] if single_x else jacs


def hessian(func, xs, batch_axis=None):
    if not callable(func):
        raise TypeError("hessian expects a callable (func-form API)")
    f = _wrap_fn(func)
    single = isinstance(xs, Tensor)
    vals = [xs._value] if single else [x._value for x in xs]
    h = jax.hessian(f, argnums=tuple(range(len(vals))))(*vals)
    if single:
        return Tensor(h[0][0], _internal=True)
    return [[Tensor(hij, _internal=True) for hij in hi] for hi in h]


def vjp(func, xs, v=None):
    """reference: python/paddle/incubate/autograd/primapi vjp."""
    f = _wrap_fn(func)
    single = isinstance(xs, Tensor)
    vals = [xs._value] if single else [x._value for x in xs]
    out, vjp_fn = jax.vjp(f, *vals)
    if v is None:
        cot = jnp.ones_like(out)
    else:
        cot = v._value if isinstance(v, Tensor) else v
    grads = vjp_fn(cot)
    gt = [Tensor(g, _internal=True) for g in grads]
    return (Tensor(out, _internal=True), gt[0] if single else gt)


def jvp(func, xs, v=None):
    f = _wrap_fn(func)
    single = isinstance(xs, Tensor)
    vals = [xs._value] if single else [x._value for x in xs]
    if v is None:
        tangents = [jnp.ones_like(x) for x in vals]
    else:
        vs = [v] if isinstance(v, Tensor) else v
        tangents = [t._value if isinstance(t, Tensor) else t for t in vs]
    out, tan = jax.jvp(f, tuple(vals), tuple(tangents))
    return Tensor(out, _internal=True), Tensor(tan, _internal=True)
