"""PyLayer: user-defined differentiable ops
(reference: python/paddle/autograd/py_layer.py — PyLayerContext:36,
PyLayer:282).

The forward/backward staticmethods run eagerly over Tensors; the tape records
a node whose vjp closure calls the user's backward. (jax.custom_vjp is the
analog for the functional/jit path — see paddle_tpu.incubate.jax_custom_vjp.)
"""
from __future__ import annotations

from typing import Any, List, Tuple

import jax.numpy as jnp

from .._core.tensor import Tensor
from .._core import autograd as ag


class PyLayerContext:
    """reference: py_layer.py:36."""

    def __init__(self):
        self._saved = ()
        self.not_inplace_tensors = ()
        self._materialize_grads = True

    def save_for_backward(self, *tensors):
        self._saved = tensors

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensor_(self):
        return self._saved

    def mark_not_inplace(self, *args):
        self.not_inplace_tensors = args

    def mark_non_differentiable(self, *args):
        self._non_diff = args

    def set_materialize_grads(self, value):
        self._materialize_grads = bool(value)


class PyLayerMeta(type):
    def __init__(cls, name, bases, attrs):
        super().__init__(name, bases, attrs)


class PyLayer(metaclass=PyLayerMeta):
    """reference: py_layer.py:282. Subclass with @staticmethod forward and
    backward; call via .apply()."""

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()

        # run user forward under no_grad: user saves tensors explicitly
        with ag.no_grad():
            outputs = cls.forward(ctx, *args, **kwargs)

        tensor_inputs = [a for a in args if isinstance(a, Tensor)]
        diff_inputs = [t for t in tensor_inputs
                       if not t.stop_gradient and
                       jnp.issubdtype(jnp.result_type(t._value),
                                      jnp.inexact)]
        track = ag.is_grad_enabled() and bool(diff_inputs)

        is_seq = isinstance(outputs, (tuple, list))
        flat_outs = list(outputs) if is_seq else [outputs]
        out_tensors = [o for o in flat_outs if isinstance(o, Tensor)]

        if not track:
            return outputs

        out_meta = [(tuple(o.shape), jnp.result_type(o._value))
                    for o in out_tensors]

        def run_backward(cot_tensors, grad_mode):
            """Invoke the user backward on Tensor cotangents and normalize
            the result to a list of Tensors (one per diff input)."""
            guard = ag.enable_grad() if grad_mode else ag.no_grad()
            with guard:
                grads = cls.backward(ctx, *cot_tensors) \
                    if len(cot_tensors) > 1 else \
                    cls.backward(ctx, cot_tensors[0])
            if not isinstance(grads, (tuple, list)):
                grads = (grads,)
            gs = [g for g in grads if g is not None]
            if len(gs) != len(diff_inputs):
                raise ValueError(
                    f"{cls.__name__}.backward returned {len(gs)} grads "
                    f"but forward had {len(diff_inputs)} differentiable "
                    "tensor inputs")
            return [g if isinstance(g, Tensor)
                    else Tensor(jnp.asarray(g), stop_gradient=True,
                                _internal=True) for g in gs]

        def vjp_fn(cots):
            if not isinstance(cots, tuple):
                cots = (cots,)
            cot_tensors = [Tensor(c, stop_gradient=True, _internal=True)
                           for c in cots]
            return tuple(g._value for g in run_backward(cot_tensors,
                                                        grad_mode=False))

        def vjp_graph_fn(cot_tensors):
            """create_graph=True path: run the user backward in grad mode
            on Tensor cotangents so its ops land on the tape.  Second
            derivatives flow through the backward fn's own computation
            (the cotangent-linear part); residuals saved under no_grad
            stay constants — the reference's ``once_differentiable``
            boundary."""
            return run_backward(cot_tensors, grad_mode=True)

        node = ag.Node(vjp_fn, diff_inputs, out_meta, len(out_tensors) > 1,
                       name=cls.__name__)
        node.vjp_graph_fn = vjp_graph_fn
        for k, o in enumerate(out_tensors):
            o._stop_gradient = False
            o._node = node
            o._out_index = k
        return outputs


class LegacyPyLayer(PyLayer):
    pass


def once_differentiable(fn):
    return fn
