"""saved_tensors_hooks (reference: python/paddle/autograd/saved_tensors_hooks.py).

On the jax substrate saved activations are immutable arrays captured in vjp
closures; the pack/unpack hook pair is honored for PyLayer-saved tensors and
kept for API parity (offload-to-host packing works via jax.device_put).
"""
from __future__ import annotations

import threading

_state = threading.local()


def get_hooks():
    return getattr(_state, "hooks", None)


class saved_tensors_hooks:
    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        self._old = get_hooks()
        _state.hooks = (self.pack_hook, self.unpack_hook)
        return self

    def __exit__(self, *exc):
        _state.hooks = self._old
        return False
