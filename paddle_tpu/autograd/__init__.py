"""paddle_tpu.autograd (reference: python/paddle/autograd/)."""
from .py_layer import PyLayer, PyLayerContext  # noqa: F401
from .functional import grad, jacobian, hessian, vjp, jvp  # noqa: F401
from .saved_tensors_hooks import saved_tensors_hooks  # noqa: F401
from .._core.autograd import backward, no_grad, enable_grad, \
    is_grad_enabled, set_grad_enabled  # noqa: F401
