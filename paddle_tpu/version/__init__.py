"""reference: python/paddle/version/__init__.py (generated at build)."""
full_version = "0.1.0"
major = "0"
minor = "1"
patch = "0"
rc = "0"
commit = "tpu-native"
with_pip_cuda_libraries = "OFF"
cuda_version = "False"
cudnn_version = "False"
tensorrt_version = None
xpu_version = "False"


def show():
    print(f"paddle_tpu {full_version} (commit {commit}) — TPU-native")


def cuda():
    return False


def cudnn():
    return False


def xpu():
    return False


def tpu():
    import jax
    try:
        return any(d.platform == "tpu" for d in jax.devices())
    except Exception:
        return False
