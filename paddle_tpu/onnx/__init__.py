"""paddle.onnx parity (reference: python/paddle/onnx/export.py — delegates
to paddle2onnx).

TPU-native: the portable serving format is StableHLO, not ONNX — XLA
consumes it directly on any backend. ``export`` traces the layer and
writes ``<path>.stablehlo.mlir`` (plus params via jit.save). If the
``onnx`` package is importable an ONNX protobuf conversion could be
plugged in; this environment ships without it, so requesting
``format="onnx"`` raises with guidance rather than silently writing a
different format.
"""
from __future__ import annotations

from typing import Optional, Sequence


def export(layer, path: str, input_spec: Optional[Sequence] = None,
           opset_version: int = 9, format: str = "stablehlo", **configs):
    if format == "onnx":
        raise RuntimeError(
            "ONNX export requires the paddle2onnx/onnx packages (not "
            "available here). Use format='stablehlo' — XLA runtimes load "
            "it directly.")
    from ..jit.save_load import save as jit_save
    jit_save(layer, path, input_spec=input_spec, **configs)
    return path + ".pdmodel"
