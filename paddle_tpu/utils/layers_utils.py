"""reference: python/paddle/utils/layers_utils.py — pytree helpers."""
from __future__ import annotations

import jax


def flatten(nest):
    leaves, _ = jax.tree.flatten(
        nest, is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))
    return leaves


def pack_sequence_as(structure, flat_sequence):
    treedef = jax.tree.structure(
        structure, is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))
    return jax.tree.unflatten(treedef, flat_sequence)


def map_structure(func, *structures):
    return jax.tree.map(
        func, *structures,
        is_leaf=lambda x: not isinstance(x, (list, tuple, dict)))
