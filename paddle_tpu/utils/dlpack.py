"""reference: python/paddle/utils/dlpack.py — zero-copy tensor exchange."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .._core.tensor import Tensor


def to_dlpack(x: Tensor):
    """Export as a DLPack-protocol object (implements __dlpack__ /
    __dlpack_device__ — the modern producer form; consumers that want the
    legacy capsule call .__dlpack__() themselves)."""
    return x._value if isinstance(x, Tensor) else jnp.asarray(x)


class _CapsuleHolder:
    """Adapts a legacy raw capsule to the modern protocol."""

    def __init__(self, cap):
        self._cap = cap

    def __dlpack__(self, **kw):
        return self._cap

    def __dlpack_device__(self):
        return (1, 0)  # kDLCPU


def from_dlpack(data) -> Tensor:
    """Import from any __dlpack__-bearing object or a legacy capsule."""
    if not hasattr(data, "__dlpack__"):
        data = _CapsuleHolder(data)
    arr = jnp.from_dlpack(data)
    return Tensor(arr, _internal=True)
