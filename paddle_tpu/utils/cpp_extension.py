"""Custom C++ op extensions.

reference: python/paddle/utils/cpp_extension/ (CppExtension/CUDAExtension/
load/setup JIT-building user .cc/.cu into loadable op libraries;
paddle/fluid/framework/custom_operator.cc registers them).

TPU-native split of the capability:
- DEVICE custom kernels are written in Pallas (`paddle_tpu.ops.pallas`) —
  that is the TPU analog of a user .cu kernel and needs no build system.
- HOST custom ops (pre/post-processing, CPU-bound logic, third-party C++
  libraries) are what this module builds: g++ compiles user sources into a
  shared library; ops are exposed through a simple C ABI and run eagerly
  via ctypes or inside ``jit`` through ``jax.pure_callback`` (XLA calls
  back to host — the reference's host kernel path). Gradients: provide a
  ``grad_symbol`` and the op becomes a ``jax.custom_vjp``.

C ABI (float32, row-major, contiguous):
  forward:  void NAME(const float* in0, ..., float* out, long long n);
  backward: void GRAD(const float* in0, ..., const float* grad_out,
                      float* grad_in0, long long n);   # unary ops only
"""
from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
from typing import List, Optional, Sequence

import numpy as np
import jax
import jax.numpy as jnp

from .._core.tensor import Tensor
from ..ops._registry import as_tensor, raw


class CppExtension:
    """reference: cpp_extension.CppExtension — a named source bundle."""

    def __init__(self, sources: Sequence[str], name: Optional[str] = None,
                 extra_compile_args=None, **kw):
        self.sources = list(sources)
        self.name = name
        self.extra_compile_args = list(extra_compile_args or [])


# CUDA does not exist here; kept so reference setup scripts import cleanly,
# pointing users at Pallas for device kernels.
def CUDAExtension(*a, **k):
    raise RuntimeError(
        "CUDAExtension has no TPU analog — write device kernels in Pallas "
        "(paddle_tpu.ops.pallas) and host ops via CppExtension/load")


def _build(name: str, sources: List[str], extra_cflags, build_directory,
           verbose: bool) -> str:
    bdir = build_directory or os.path.join(
        os.path.expanduser("~"), ".cache", "paddle_tpu_extensions")
    os.makedirs(bdir, exist_ok=True)
    h = hashlib.sha256()
    for s in sources:
        with open(s, "rb") as f:
            h.update(f.read())
    h.update(" ".join(extra_cflags or []).encode())
    out = os.path.join(bdir, f"{name}_{h.hexdigest()[:16]}.so")
    if not os.path.exists(out):
        # unique tmp: concurrent builders (pytest-xdist, multi-process
        # launch) must not race each other's g++ output
        tmp = f"{out}.{os.getpid()}.tmp"
        cmd = ["g++", "-O3", "-std=c++17", "-shared", "-fPIC",
               *(extra_cflags or []), *sources, "-o", tmp]
        if verbose:
            print("[cpp_extension]", " ".join(cmd))
        try:
            try:
                proc = subprocess.run(cmd, capture_output=True, text=True)
            except FileNotFoundError as e:
                raise RuntimeError(
                    f"building extension {name!r} failed: g++ not found "
                    f"({e})") from e
            if proc.returncode != 0:
                raise RuntimeError(
                    f"building extension {name!r} failed:\n{proc.stderr}")
            os.replace(tmp, out)
        finally:
            if os.path.exists(tmp):
                os.remove(tmp)
    return out


class ExtensionModule:
    """A loaded custom-op library; ``custom_op`` wraps C symbols into
    framework ops."""

    def __init__(self, name: str, path: str):
        self.name = name
        self.path = path
        self._lib = ctypes.CDLL(path)

    def _sym(self, symbol: str, n_ptr: int):
        fn = getattr(self._lib, symbol)
        fn.restype = None
        fn.argtypes = [ctypes.c_void_p] * n_ptr + [ctypes.c_longlong]
        return fn

    def custom_op(self, symbol: str, num_inputs: int = 1,
                  grad_symbol: Optional[str] = None):
        """Wrap C symbol into an op usable eagerly and under jit.
        Output shape/dtype = first input's (elementwise ABI). Gradients
        need ``grad_symbol`` (unary ops)."""
        fwd_fn = self._sym(symbol, num_inputs + 1)
        if grad_symbol is not None and num_inputs != 1:
            raise ValueError("grad_symbol is supported for unary ops")
        bwd_fn = self._sym(grad_symbol, 3) if grad_symbol else None

        def host_fwd(*arrays):
            arrs = [np.ascontiguousarray(np.asarray(a), np.float32)
                    for a in arrays]
            for i, a in enumerate(arrs[1:], 1):
                if a.shape != arrs[0].shape:
                    raise ValueError(
                        f"{symbol}: input {i} shape {a.shape} != input 0 "
                        f"shape {arrs[0].shape} (elementwise C ABI)")
            out = np.empty_like(arrs[0])
            fwd_fn(*[a.ctypes.data_as(ctypes.c_void_p) for a in arrs],
                   out.ctypes.data_as(ctypes.c_void_p), out.size)
            return out

        def host_bwd(x, gy):
            xa = np.ascontiguousarray(np.asarray(x), np.float32)
            ga = np.ascontiguousarray(np.asarray(gy), np.float32)
            gx = np.empty_like(xa)
            bwd_fn(xa.ctypes.data_as(ctypes.c_void_p),
                   ga.ctypes.data_as(ctypes.c_void_p),
                   gx.ctypes.data_as(ctypes.c_void_p), gx.size)
            return gx

        def call_fwd(*raws):
            if not any(isinstance(r, jax.core.Tracer) for r in raws):
                # eager: straight ctypes on host buffers, no callback
                # round-trip (docstring contract)
                return jnp.asarray(host_fwd(*raws))
            spec = jax.ShapeDtypeStruct(raws[0].shape, jnp.float32)
            return jax.pure_callback(host_fwd, spec, *raws,
                                     vmap_method="sequential")

        if bwd_fn is not None:
            @jax.custom_vjp
            def op_val(x):
                return call_fwd(x)

            def op_val_fwd(x):
                return call_fwd(x), x

            def op_val_bwd(x, gy):
                spec = jax.ShapeDtypeStruct(x.shape, jnp.float32)
                return (jax.pure_callback(host_bwd, spec, x, gy,
                                          vmap_method="sequential"),)
            op_val.defvjp(op_val_fwd, op_val_bwd)

            def op(x, name=None):
                # route through apply so .backward() sees the custom vjp
                from .._core.autograd import apply
                return apply(op_val, as_tensor(x), name=symbol)
        else:
            def op(*tensors, name=None):
                raws = [raw(as_tensor(t)) for t in tensors]
                out = call_fwd(*raws)
                t = Tensor(out, _internal=True)
                t.stop_gradient = True  # no grad_symbol -> non-differentiable
                return t
        op.__name__ = symbol
        return op


def load(name: str, sources: Sequence[str], extra_cxx_flags=None,
         extra_cflags=None, build_directory: Optional[str] = None,
         verbose: bool = False, **kw) -> ExtensionModule:
    """reference: cpp_extension.load — JIT-build + load a custom-op
    library."""
    flags = list(extra_cxx_flags or extra_cflags or [])
    path = _build(name, list(sources), flags, build_directory, verbose)
    return ExtensionModule(name, path)


def setup(name: Optional[str] = None, ext_modules=None, **kw):
    """reference: cpp_extension.setup — build the extensions in place and
    return the loaded modules (the reference installs an importable
    package; here the returned ExtensionModules are the artifact)."""
    exts = ext_modules if isinstance(ext_modules, (list, tuple)) \
        else [ext_modules]
    out = []
    for ext in exts:
        if ext is None:
            continue
        out.append(load(ext.name or name or "custom_ext", ext.sources,
                        extra_cxx_flags=ext.extra_compile_args))
    return out[0] if len(out) == 1 else out
