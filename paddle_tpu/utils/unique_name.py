"""reference: python/paddle/utils/unique_name.py — generate/guard/switch."""
from __future__ import annotations

import contextlib
import threading

_state = threading.local()


def _gens():
    if not hasattr(_state, "gens"):
        _state.gens = [{}]
    return _state.gens


def generate(key: str) -> str:
    cur = _gens()[-1]
    cur[key] = cur.get(key, -1) + 1
    return f"{key}_{cur[key]}"


def generate_with_ignorable_key(key: str) -> str:
    return generate(key)


def switch(new_generator=None):
    old = _gens()[-1]
    _gens()[-1] = new_generator if new_generator is not None else {}
    return old


@contextlib.contextmanager
def guard(new_generator=None):
    _gens().append(new_generator if isinstance(new_generator, dict) else {})
    try:
        yield
    finally:
        _gens().pop()
