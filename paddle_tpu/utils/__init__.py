"""paddle.utils parity (reference: python/paddle/utils/ — deprecated
decorator, try_import, require_version, download, dlpack, unique_name,
layers_utils flatten/pack_sequence_as)."""
from __future__ import annotations

import functools
import importlib
import warnings

from . import unique_name  # noqa: F401
from . import dlpack  # noqa: F401
from .layers_utils import flatten, pack_sequence_as, map_structure  # noqa: F401


def deprecated(update_to="", since="", reason="", level=0):
    """reference: utils/deprecated.py."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            msg = (f"API {fn.__module__}.{fn.__name__} is deprecated "
                   f"since {since}")
            if update_to:
                msg += f", use {update_to} instead"
            if reason:
                msg += f". Reason: {reason}"
            if level == 2:
                raise RuntimeError(msg)
            warnings.warn(msg, DeprecationWarning, stacklevel=2)
            return fn(*args, **kwargs)
        return wrapper
    return deco


def try_import(module_name, err_msg=None):
    """reference: utils/lazy_import.py try_import."""
    try:
        return importlib.import_module(module_name)
    except ImportError:
        raise ImportError(err_msg or
                          f"Failed to import {module_name}. Install it "
                          f"before using this feature.")


def require_version(min_version, max_version=None):
    """reference: utils/install_check.py require_version."""
    from .. import __version__

    def parse(v):
        return tuple(int(x) for x in str(v).split(".")[:3])
    cur = parse(__version__)
    if parse(min_version) > cur:
        raise Exception(f"paddle_tpu>={min_version} required, got "
                        f"{__version__}")
    if max_version and parse(max_version) < cur:
        raise Exception(f"paddle_tpu<={max_version} required, got "
                        f"{__version__}")


def run_check():
    """reference: utils/install_check.py run_check — smoke the device."""
    import numpy as np
    import paddle_tpu as paddle
    x = paddle.to_tensor(np.ones((2, 2), np.float32))
    y = (x @ x).numpy()
    assert np.allclose(y, 2 * np.ones((2, 2)))
    import jax
    dev = jax.devices()[0]
    print(f"paddle_tpu is installed successfully! device={dev.platform} "
          f"({getattr(dev, 'device_kind', '?')})")
from . import cpp_extension  # noqa: F401
