"""Headline benchmark: flagship LM training throughput on one chip.

Metric (BASELINE.md north star): tokens/sec/chip + MFU on a Llama-style
decoder LM, seq=4096, bf16, flash attention, remat, fused AdamW — the
single-chip row of the reference's hybrid-parallel Llama recipe. The
reference publishes no in-tree numbers (BASELINE.json "published": {}), so
vs_baseline is reported against the 40%-MFU north star.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import json
import time

import numpy as np
import jax
import jax.numpy as jnp


def pick_config():
    """Size the model to the available chip (HBM-bound).

    Persistent state is 14 B/param (bf16 param + fp32 master/m/v) plus a
    transient fp32 grad tree and the fp32 logits — a ~660M model with
    batch 2 × seq 4096 fits a 16G-HBM chip (v5e) with headroom; larger
    chips could scale up, but this config keeps the bench portable.
    """
    from paddle_tpu.models import llama
    dev = jax.devices()[0]
    if dev.platform == "tpu":
        return llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=20, num_heads=12, num_kv_heads=12, max_seq_len=4096,
            dtype=jnp.bfloat16, remat=True), 4096, 4
    # CPU fallback (driver smoke / local runs)
    return llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256), 256, 2


def peak_flops(dev) -> float:
    if dev.platform != "tpu":
        return 1e12
    kind = getattr(dev, "device_kind", "").lower()
    table = {  # bf16 peak per chip
        "v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5p": 459e12,
        "v6e": 918e12, "v6 lite": 918e12, "trillium": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 275e12


def main():
    from paddle_tpu.models import llama, train

    cfg, seq, batch = pick_config()
    on_tpu = jax.devices()[0].platform == "tpu"
    step = train.make_train_step(cfg, seq_chunk=512 if on_tpu else None)
    state = jax.jit(lambda k: train.init_train_state(k, cfg))(
        jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq)), jnp.int32)

    # warmup / compile; sync via host transfer (block_until_ready is not a
    # reliable fence through the remote-dispatch tunnel)
    state, m = step(state, tokens)
    float(m["loss"])
    state, m = step(state, tokens)
    float(m["loss"])

    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, tokens)
    lossv = float(m["loss"])
    dt = (time.perf_counter() - t0) / iters

    toks = batch * seq
    tps = toks / dt
    mfu = tps * cfg.flops_per_token(seq) / peak_flops(jax.devices()[0])
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "seq": seq, "batch": batch,
                  "params": cfg.num_params(),
                  "device": str(jax.devices()[0].device_kind),
                  "loss": lossv},
    }))


if __name__ == "__main__":
    main()
