"""Headline benchmark: flagship LM training throughput on one chip.

Metric (BASELINE.md north star): tokens/sec/chip + MFU on a Llama-style
decoder LM, seq=4096, bf16, flash attention, remat, fused AdamW — the
single-chip row of the reference's hybrid-parallel Llama recipe. The
reference publishes no in-tree numbers (BASELINE.json "published": {}), so
vs_baseline is reported against the 40%-MFU north star.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The measurement runs in a child process under a watchdog timeout; the parent
retries transient backend-init failures (the TPU tunnel can be flaky) and
ALWAYS prints exactly one JSON line — with an ``"error"`` field if every
attempt failed — so the driver has something to parse no matter what.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time
from typing import Optional


def pick_config():
    """Size the model to the available chip (HBM-bound).

    Persistent state is 14 B/param (bf16 param + fp32 master/m/v) plus a
    transient fp32 grad tree and the fp32 logits — a ~660M model with
    batch 2 × seq 4096 fits a 16G-HBM chip (v5e) with headroom; larger
    chips could scale up, but this config keeps the bench portable.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import llama
    dev = jax.devices()[0]
    if dev.platform == "tpu":
        # measured on 16G v5e: batch 4 fits with headroom at 54% MFU.
        # bigger-HBM chips (v5p 95G, v6e 32G) scale the batch so the MXU
        # stays fed; model stays fixed for cross-chip comparability
        batch = 4
        try:
            hbm = dev.memory_stats().get("bytes_limit", 16 << 30)
            # round against the NOMINAL tier: real bytes_limit sits a few
            # percent under the marketing number (XLA reserves HBM), so
            # floor division would strand a 32G chip on the 16G tier
            batch = max(4, min(16, 4 * round(hbm / (16 << 30))))
        except Exception:
            pass
        return llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=20, num_heads=12, num_kv_heads=12, max_seq_len=4096,
            dtype=jnp.bfloat16, remat=True), 4096, batch
    # CPU fallback (driver smoke / local runs)
    return llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256), 256, 2


def peak_flops(dev) -> float:
    if dev.platform != "tpu":
        return 1e12
    kind = getattr(dev, "device_kind", "").lower()
    table = {  # bf16 peak per chip
        "v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5p": 459e12,
        "v6e": 918e12, "v6 lite": 918e12, "trillium": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 275e12


def _result(tps, mfu, seq, batch, cfg, lossv, decode_tps):
    import jax
    return {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "seq": seq, "batch": batch,
                  "params": cfg.num_params(),
                  "device": str(jax.devices()[0].device_kind),
                  "loss": lossv,
                  "decode_tokens_per_sec": decode_tps},
    }


def measure():
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import train

    t_measure_start = time.perf_counter()
    cfg, seq, batch = pick_config()
    on_tpu = jax.devices()[0].platform == "tpu"
    step = train.make_train_step(cfg, seq_chunk=512 if on_tpu else None)
    state = jax.jit(lambda k: train.init_train_state(k, cfg))(
        jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq)), jnp.int32)

    # warmup / compile; sync via host transfer (block_until_ready is not a
    # reliable fence through the remote-dispatch tunnel)
    state, m = step(state, tokens)
    float(m["loss"])
    state, m = step(state, tokens)
    float(m["loss"])

    iters = 10 if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, tokens)
    lossv = float(m["loss"])
    dt = (time.perf_counter() - t0) / iters

    toks = batch * seq
    tps = toks / dt
    mfu = tps * cfg.flops_per_token(seq) / peak_flops(jax.devices()[0])

    # serving path: batched KV-cache decode throughput (reference decode
    # benches run block_multi_head_attention; here the pallas decode kernel)
    decode_tps = None
    # the decode extra costs two more jit compiles; never let it push the
    # run past the parent watchdog — the headline number must survive
    budget = int(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "600"))
    elapsed = time.perf_counter() - t_measure_start
    if elapsed > 0.35 * budget:
        return _result(tps, mfu, seq, batch, cfg, lossv, None)
    try:
        from paddle_tpu.models import generate as gen
        db, dp_len, dnew = (8, 128, 64) if on_tpu else (2, 8, 8)
        prompt = jnp.asarray(np.random.default_rng(1).integers(
            0, cfg.vocab_size, (db, dp_len)), jnp.int32)
        def make(n):
            f = jax.jit(lambda pr: gen.generate(
                state.params, pr, cfg, max_new_tokens=n, temperature=0.0))
            f(prompt).block_until_ready()      # compile
            return f

        def timed(f):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                f(prompt).block_until_ready()
                best = min(best, time.perf_counter() - t0)
            return best
        g_full, g_one = make(dnew), make(1)
        # subtract the prefill+1 run so the rate is pure decode steps
        ddt = timed(g_full) - timed(g_one)
        if ddt <= 0:  # tiny CPU smoke configs: noise swamps the delta
            ddt = timed(g_full)
        decode_tps = round(db * (dnew - 1) / ddt, 2)
    except Exception:
        pass  # decode bench is auxiliary; never kill the headline number

    return _result(tps, mfu, seq, batch, cfg, lossv, decode_tps)


def child_main():
    plat = os.environ.get("PADDLE_TPU_BENCH_PLATFORM")
    if plat:  # local/CI smoke runs; driver runs on the real chip
        import jax
        jax.config.update("jax_platforms", plat)
    result = measure()
    print(json.dumps(result))
    sys.stdout.flush()
    os._exit(0)  # skip hanging plugin destructors at interpreter exit


def probe_backend(timeout_s: int) -> Optional[str]:
    """Fast tunnel health check: a throwaway child just initializes the
    backend. Returns None when healthy, else an error string — so a dead
    TPU tunnel costs ~probe-timeout per attempt instead of the full
    measurement watchdog (the observed failure mode: jax.devices() hangs
    indefinitely when the tunnel is down)."""
    if os.environ.get("PADDLE_TPU_BENCH_PLATFORM"):
        return None  # forced-platform smoke runs skip the probe
    code = ("import jax, os, sys; d = jax.devices(); "
            "print('PROBE_OK', d[0].platform, len(d)); "
            "sys.stdout.flush(); os._exit(0)")  # skip plugin destructors
    try:
        proc = subprocess.run([sys.executable, "-c", code],
                              stdout=subprocess.PIPE,
                              stderr=subprocess.STDOUT, text=True,
                              timeout=timeout_s)
    except subprocess.TimeoutExpired as e:
        # a hung EXIT after a successful init still proves the backend
        out = (e.stdout or b"")
        if isinstance(out, bytes):
            out = out.decode(errors="replace")
        if "PROBE_OK" in out:
            return None
        return f"backend probe hung >{timeout_s}s (TPU tunnel down?)"
    if "PROBE_OK" not in proc.stdout:
        tail = proc.stdout.strip().splitlines()[-3:]
        return f"backend probe failed: {' | '.join(tail)[-400:]}"
    return None


def parent_main():
    """Run the measurement in a watchdog-guarded child; retry transient
    backend-init failures; ALWAYS print exactly one JSON line."""
    attempts = int(os.environ.get("PADDLE_TPU_BENCH_ATTEMPTS", "5"))
    timeout_s = int(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "600"))
    probe_s = int(os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT", "150"))
    last_err = "unknown"
    for i in range(attempts):
        perr = probe_backend(probe_s)
        if perr is not None:
            last_err = f"attempt {i + 1}: {perr}"
            if i + 1 < attempts:
                # a flaky tunnel often recovers on the order of minutes;
                # the probe itself is cheap, so wait meaningfully between
                # attempts (total patience ~= attempts * (probe + 60s))
                time.sleep(60)
            continue
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
                timeout=timeout_s,
                cwd=os.path.dirname(os.path.abspath(__file__)))
        except subprocess.TimeoutExpired:
            last_err = f"attempt {i + 1}: watchdog timeout after {timeout_s}s"
            continue
        for line in reversed(proc.stdout.strip().splitlines()):
            try:
                parsed = json.loads(line)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(parsed, dict) and "metric" in parsed:
                print(line)
                sys.stdout.flush()
                os._exit(0)
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-15:]
        last_err = (f"attempt {i + 1}: rc={proc.returncode}; "
                    + " | ".join(tail)[-1500:])
        if i + 1 < attempts:
            time.sleep(5 * (i + 1))  # backoff before retrying a flaky tunnel
    print(json.dumps({
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
        "error": last_err,
    }))
    sys.stdout.flush()
    os._exit(1)


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main()
    parent_main()
