"""Headline benchmark: flagship LM training throughput on one chip.

Metric (BASELINE.md north star): tokens/sec/chip + MFU on a Llama-style
decoder LM, seq=4096, bf16, flash attention, remat, fused AdamW — the
single-chip row of the reference's hybrid-parallel Llama recipe. The
reference publishes no in-tree numbers (BASELINE.json "published": {}), so
vs_baseline is reported against the 40%-MFU north star.

Prints ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The measurement runs in a child process under a watchdog timeout; the parent
retries transient backend-init failures (the TPU tunnel can be flaky) and
ALWAYS prints exactly one JSON line — with an ``"error"`` field if every
attempt failed — so the driver has something to parse no matter what.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time
from typing import Optional


def pick_config():
    """Size the model to the available chip (HBM-bound).

    Persistent state is 14 B/param (bf16 param + fp32 master/m/v) plus a
    transient fp32 grad tree and the fp32 logits — a ~660M model with
    batch 2 × seq 4096 fits a 16G-HBM chip (v5e) with headroom; larger
    chips could scale up, but this config keeps the bench portable.
    """
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import llama
    dev = jax.devices()[0]
    if dev.platform == "tpu":
        # measured on 16G v5e: batch 4 fits with headroom at 54% MFU.
        # bigger-HBM chips (v5p 95G, v6e 32G) scale the batch so the MXU
        # stays fed; model stays fixed for cross-chip comparability
        batch = 4
        try:
            hbm = dev.memory_stats().get("bytes_limit", 16 << 30)
            # round against the NOMINAL tier: real bytes_limit sits a few
            # percent under the marketing number (XLA reserves HBM), so
            # floor division would strand a 32G chip on the 16G tier
            batch = max(4, min(16, 4 * round(hbm / (16 << 30))))
        except Exception:
            pass
        return llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=20, num_heads=12, num_kv_heads=12, max_seq_len=4096,
            dtype=jnp.bfloat16, remat=True), 4096, batch
    # CPU fallback (driver smoke / local runs)
    return llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256), 256, 2


_XLA_CACHE_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                              "artifacts", "xla_cache")


def enable_persistent_compilation_cache(path: Optional[str] = None):
    """Point JAX's persistent compilation cache at
    ``artifacts/xla_cache/`` (VERDICT r5 top_next: five rounds of rc=1
    are an OPS problem — a short tunnel window must bank every decode
    tier instead of burning itself on recompiles; with the cache, a
    re-run after a watchdog kill re-loads the programs the killed run
    already compiled). Shared by bench.py, tools/decode_bench.py and —
    via the ``JAX_COMPILATION_CACHE_DIR`` env this helper honors —
    tools/tpu_watch.sh and tools/aot_validate.py.

    Every compile persists (min-time/min-size thresholds zeroed): the
    serving programs are individually small but numerous — the bucketed
    chunk/verify grid is exactly the long tail the default 1s threshold
    would skip. Returns the cache dir, or None when setup failed (the
    measurement still runs, uncached — never fail a bench over cache
    plumbing)."""
    try:
        import jax
        path = (path or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                or _XLA_CACHE_DIR)
        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        return path
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        print(f"persistent compilation cache unavailable: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return None


_WINNER = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "PERF_WINNER.json")


_SWEEP_BASE_BATCH = 4   # every sweep variant was measured vs this base


def _apply_perf_winner(cfg, batch, seq_chunk):
    """Adopt the measured sweep winner (tools/perf_sweep.py writes
    PERF_WINNER.json when a variant beats base by >2%) so the watcher's
    tuning reaches the driver's end-of-round bench without a manual
    config flip. Every field is VALIDATED before anything mutates —
    stale (>48h), malformed, or out-of-vocabulary records are ignored
    whole (a half-adopted config no sweep measured must never run)."""
    try:
        with open(_WINNER) as f:
            rec = json.load(f)
        if time.time() - rec.get("recorded_unix", 0) > 48 * 3600:
            return cfg, batch, seq_chunk
        v = rec["variant"]
        policy = v.get("policy", cfg.remat_policy)
        fused = v.get("fused", cfg.fused_kernels)
        wbatch = int(v.get("batch", batch))
        wchunk = v.get("seq_chunk", seq_chunk)
        if policy not in ("nothing", "attn", "dots") or \
                fused not in ("xla", "auto", "pallas") or \
                not (1 <= wbatch <= 64) or \
                not (wchunk is None or isinstance(wchunk, int)):
            return cfg, batch, seq_chunk
        cfg = dataclasses.replace(
            cfg, remat=bool(v.get("remat", cfg.remat)),
            remat_policy=policy, fused_kernels=fused)
        # winner batches were measured on the 16G sweep base; a chip
        # whose HBM scaled the batch ABOVE the base keeps its scaling
        # (forcing a v5e-sized batch onto a v5p would halve tokens/s)
        if batch == _SWEEP_BASE_BATCH:
            batch = wbatch
        seq_chunk = wchunk
        print(f"bench: adopting sweep winner {v.get('name')} "
              f"(+{100 * rec.get('gain', 0):.1f}% vs base)",
              file=sys.stderr)
    except Exception:
        pass
    return cfg, batch, seq_chunk


def peak_flops(dev) -> float:
    if dev.platform != "tpu":
        return 1e12
    kind = getattr(dev, "device_kind", "").lower()
    table = {  # bf16 peak per chip
        "v4": 275e12, "v5e": 197e12, "v5 lite": 197e12, "v5p": 459e12,
        "v6e": 918e12, "v6 lite": 918e12, "trillium": 918e12,
    }
    for k, v in table.items():
        if k in kind:
            return v
    return 275e12


def _result(tps, mfu, seq, batch, cfg, lossv, decode_tps,
            decode_int8_tps=None, decode_int4_tps=None,
            decode_w8kv8_tps=None, decode_paged_tps=None,
            decode_prefix_tps=None, decode_sched=None,
            decode_spec=None, decode_treespec=None, decode_tp=None,
            decode_tp2d=None,
            decode_cluster=None, decode_multiproc=None,
            decode_offload=None, decode_slo=None, decode_fused=None,
            decode_multilora=None, phases=None):
    import jax
    rec = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": round(tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(mfu / 0.40, 4),
        "extra": {"mfu": round(mfu, 4), "seq": seq, "batch": batch,
                  "params": cfg.num_params(),
                  "device": str(jax.devices()[0].device_kind),
                  "loss": lossv,
                  "decode_tokens_per_sec": decode_tps,
                  "decode_int8_tokens_per_sec": decode_int8_tps,
                  "decode_int4_tokens_per_sec": decode_int4_tps,
                  "decode_w8kv8_tokens_per_sec": decode_w8kv8_tps,
                  "decode_paged_tokens_per_sec": decode_paged_tps,
                  "decode_prefix_tokens_per_sec": decode_prefix_tps,
                  "decode_sched_tokens_per_sec": (
                      decode_sched[0] if decode_sched else None),
                  "decode_spec_tokens_per_sec": (
                      decode_spec[0] if decode_spec else None),
                  "decode_treespec_tokens_per_sec": (
                      decode_treespec[0] if decode_treespec else None),
                  "decode_tp_tokens_per_sec": (
                      decode_tp[0] if decode_tp else None),
                  "decode_tp2d_tokens_per_sec": (
                      decode_tp2d[0] if decode_tp2d else None),
                  "decode_cluster_tokens_per_sec": (
                      decode_cluster[0] if decode_cluster else None),
                  "decode_offload_tokens_per_sec": (
                      decode_offload[0] if decode_offload else None),
                  "decode_slo_goodput_tokens_per_sec": (
                      decode_slo[0] if decode_slo else None),
                  "decode_multilora_tokens_per_sec": (
                      decode_multilora[0] if decode_multilora
                      else None)},
    }
    if decode_sched:
        # the tier's point is the BOUND, not just the throughput:
        # p50/p99 step latency under the bursty two-priority workload
        rec["extra"]["decode_sched_step_ms"] = decode_sched[1]
        if len(decode_sched) > 2 and decode_sched[2]:
            # overlap rider (ISSUE 12): the same workload through the
            # double-buffered scheduler — sync vs overlapped step ms +
            # the host_overhead_fraction the overlap hides
            rec["extra"]["decode_overlap_speedup"] = decode_sched[2]
        if len(decode_sched) > 3 and decode_sched[3]:
            # durability rider (ISSUE 15): the same workload through a
            # WAL-backed supervisor at each fsync rung vs journal-off —
            # the measured cost of crash durability
            rec["extra"]["decode_durability_overhead"] = decode_sched[3]
        if len(decode_sched) > 4 and decode_sched[4]:
            # trace rider (ISSUE 16): the same workload with request
            # tracing ON vs the plain run — the measured price of the
            # always-on observability switch
            rec["extra"]["decode_trace_overhead"] = decode_sched[4]
    if decode_spec:
        # the speculative tier's throughput only means something next
        # to the acceptance rate that produced it — they travel together
        rec["extra"]["decode_spec_acceptance"] = decode_spec[1]
    if decode_treespec:
        # the tree tier's throughput only means something next to the
        # realized accepted path length and the tree geometry that
        # produced it (ISSUE 20) — they ride the record together
        rec["extra"]["decode_treespec_stats"] = decode_treespec[1]
    if decode_tp:
        # the tp tier reports an AGGREGATE over tp chips: the scaling
        # factor vs the single-chip paged tier is the honest headline
        rec["extra"]["decode_tp_scaling"] = decode_tp[1]
    if decode_tp2d:
        # the 2-D mesh tier's honest headline is the dp batch-scaling
        # factor vs the 1-D tp tier at the same per-shard geometry —
        # {tp, dp, vs_1d_tp} travel with the aggregate number
        rec["extra"]["decode_tp2d_scaling"] = decode_tp2d[1]
    if decode_cluster:
        # the cluster tier's ratio vs one engine on the same tenant
        # workload (router+handoff overhead on one host, the scaling
        # win on real multi-chip deployments) travels with the number
        rec["extra"]["decode_cluster_scaling"] = decode_cluster[1]
    if decode_multiproc:
        # multi-process rider (ISSUE 19): the price of running the
        # cluster's replicas as real processes behind the socket RPC
        # control plane — rpc wall per step, handoff wire cost and the
        # vs-in-process ratio travel with the cluster tier
        rec["extra"]["decode_multiproc_overhead"] = decode_multiproc
    if decode_offload:
        # the host-tier tier's point is the RESUME cost it removed:
        # swap-in latency + the ratio vs the replay-prefill baseline
        rec["extra"]["decode_offload_resume"] = decode_offload[1]
    if decode_slo:
        # goodput only means something next to the SLO outcomes and
        # autoscale activity that produced it (ISSUE 13) — they ride
        # the record together
        rec["extra"]["decode_slo_metrics"] = decode_slo[1]
    if decode_fused:
        # fused-kernel rider on the paged tier (ISSUE 11): per-step
        # wall ms unfused vs fused + the throughput ratio — the direct
        # measurement of the Pallas fusions' HBM win
        rec["extra"]["decode_fused_speedup"] = decode_fused
    if decode_multilora:
        # the multi-LoRA tier's throughput only means something next
        # to the adapter traffic the pool absorbed (ISSUE 14): variant
        # population, slot hits, demote/promote churn and the ratio vs
        # the one-variant merged-model deployment it replaces
        rec["extra"]["decode_multilora_density"] = decode_multilora[1]
    if phases is not None:
        rec["phases"] = phases
    return _backfill_decode(rec)


def _capture_phases(step, state, tokens, cfg):
    """Instrumented mini-pass AFTER the timed measurement: one train
    step + one small eager generate() under observability + a Profiler,
    yielding the per-phase summary dict that rides each round's JSON
    under ``phases`` — so BENCH_r*.json shows where train/prefill/decode
    time went, not just end-to-end tiers. Never allowed to damage the
    headline: any failure returns None.

    The process-global registry is CLEARED first so the snapshot holds
    only this capture (a PADDLE_TPU_METRICS=1 run would otherwise leak
    trace-time junk from the jitted decode tiers into the round JSON);
    bench is a dedicated child process, so nothing else owns it. The
    prior enabled-state is restored on the way out."""
    import numpy as np
    import jax.numpy as jnp
    p = None
    was_enabled = False
    try:
        from paddle_tpu import observability as obs
        from paddle_tpu import profiler as prof
        from paddle_tpu.models import generate as gen
        was_enabled = obs.metrics_enabled()
        obs.REGISTRY.clear()
        obs.enable()
        p = prof.Profiler()
        p.start()
        with prof.RecordEvent("Train.step", "Operator"):
            state2, m2 = step(state, tokens)
            float(m2["loss"])           # host fence
        prompt = jnp.asarray(np.random.default_rng(7).integers(
            0, cfg.vocab_size, (2, 8)), jnp.int32)
        # eager call: the prefill/decode instrumentation inside
        # generate() times real work (jit would record trace time)
        np.asarray(gen.generate(state.params, prompt, cfg,
                                max_new_tokens=4, temperature=0.0))
        p.step()
        return p.phase_summary()
    except Exception as e:
        print(f"phase capture failed: {type(e).__name__}: {e}"[:300],
              file=sys.stderr)
        return None
    finally:
        # a mid-capture failure must not leave the collector recording,
        # and a PADDLE_TPU_METRICS=1 opt-in must survive the capture
        try:
            if p is not None:
                p.stop()
        except Exception:
            pass
        try:
            from paddle_tpu import observability as obs
            if not was_enabled:
                obs.disable()
        except Exception:
            pass


def _engine_tier(params, cfg, db, dnew, max_len, on_tpu, make_prompts,
                 between_passes=None, **engine_kwargs):
    """Shared engine-tier measurement scaffold (paged + prefix tiers):
    2x-oversubscribed queue with alternating decode budgets — short
    rows retire mid-run and queued prompts admit into the freed slots,
    exercising the continuous-batching mechanism itself. One warm pass
    (compiles + trie), one timed steady-state pass; ``make_prompts()``
    is called PER PASS so a tier can regenerate its unique parts (the
    prefix tier must not let the warm pass's full prompts recache), and
    ``between_passes(eng)`` — if given — runs after the warm pass so a
    tier can snapshot engine counters the timed pass should be deltaed
    against (the spec tier's acceptance record). Throughput includes
    the host scheduling loop (an ENGINE number, not a kernel
    microbench). Keeping ONE scaffold guarantees the tiers whose delta
    is reported stay comparable by construction. Returns ``(tokens/sec,
    engine)`` — the engine so tiers can read post-run stats.
    ``per_request_kw(i)`` — if given — returns extra ``submit`` kwargs
    for the i-th request of each pass (the multi-LoRA tier's per-row
    ``adapter_id``)."""
    from paddle_tpu.inference.predictor import ContinuousBatchingEngine
    per_request_kw = engine_kwargs.pop("per_request_kw", None)
    eng = ContinuousBatchingEngine(
        params, cfg, max_batch=db, page_size=16 if on_tpu else 8,
        max_len=max_len, **engine_kwargs)

    def one_pass():
        reqs = [eng.submit(p, max_new_tokens=(
            dnew if i % 2 else max(dnew // 2, 1)),
            **(per_request_kw(i) if per_request_kw else {}))
                for i, p in enumerate(make_prompts())]
        eng.run()
        return sum(r.max_new_tokens for r in reqs)

    one_pass()                                      # compile/warm pass
    if between_passes is not None:
        between_passes(eng)
    t0 = time.perf_counter()
    toks_out = one_pass()                           # steady state
    return round(toks_out / (time.perf_counter() - t0), 2), eng


def paged_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                      kv_cache_dtype=None, fused_rider=True):
    """The decode_paged_tokens_per_sec measurement, shared by measure()
    and tools/decode_bench.py so the two sources stay comparable:
    mixed prompt lengths through the :func:`_engine_tier` scaffold.
    The prefix cache is OFF: this tier is the paged-engine baseline the
    prefix tier's delta is measured against (the warm pass resubmits
    the same prompts, so a warm trie would silently convert the timed
    pass into a prefix-hit workload).

    Returns ``(tokens_per_sec, decode_fused_speedup)`` (ISSUE 11): the
    rider re-runs the IDENTICAL workload with the fused Pallas serving
    kernels on (``fused=True`` — in-VMEM q-RoPE + KV dequant in the
    decode kernel, flash chunk attention behind prefill) and reports
    per-step wall ms for both paths plus the throughput ratio — the
    direct measurement of what the fusions buy at this geometry. The
    rider is best-effort: a fused-path failure leaves the baseline
    number standing with the rider None."""
    import numpy as np
    plens = [dp_len if i % 2 else max(dp_len // 2, 1)
             for i in range(2 * db)]
    rngp = np.random.default_rng(2)
    prompts = [rngp.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in plens]

    def run(fused):
        info = {}

        def snap(eng):
            info["s0"], info["t0"] = eng._steps, time.perf_counter()

        tps, eng = _engine_tier(
            params, cfg, db, dnew, dp_len + dnew, on_tpu,
            lambda: prompts, between_passes=snap,
            kv_cache_dtype=kv_cache_dtype, enable_prefix_cache=False,
            fused=fused)
        steps = max(eng._steps - info["s0"], 1)
        step_ms = (time.perf_counter() - info["t0"]) * 1e3 / steps
        return tps, round(step_ms, 3)

    tps, step_ms = run(False)
    rider = None
    if not fused_rider:
        # budget-guarded skip (measure()/decode_bench gate it like any
        # other optional tier): the baseline number must never pay for
        # its own rider on a slow-compile day
        return tps, rider
    try:
        fused_tps, fused_ms = run(True)
        rider = {"fused_tokens_per_sec": fused_tps,
                 "unfused_step_ms": step_ms,
                 "fused_step_ms": fused_ms,
                 "speedup": round(fused_tps / tps, 3) if tps else None}
    except Exception as e:
        print(f"fused paged tier failed: {type(e).__name__}: {e}"[:300],
              file=sys.stderr)
    return tps, rider


def lowbit_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                       weight_bits, kv_cache_dtype=None):
    """The decode_int4_tokens_per_sec / decode_w8kv8_tokens_per_sec
    measurement (ISSUE 11), shared by measure() and
    tools/decode_bench.py so the two sources stay comparable.

    The PAGED ENGINE's mixed-length workload (identical mix /
    oversubscription / page-size rule as decode_paged — the tier it is
    deltaed against) with LOW-BIT weights: ``weight_bits=4`` is the
    per-group-int4 tier (quarter weight bytes — decode is HBM-bound,
    so the ratio vs decode_paged at the same lengths IS the
    weight-bandwidth win), ``weight_bits=8`` with
    ``kv_cache_dtype="int8"`` the w8/kv8 tier (weight AND KV bytes
    halved). Until this tier landed both slots were measured on the
    DENSE generate() path and had never produced a live number; the
    engine tier is what the serving tower actually ships. Prefix cache
    OFF (the paged-tier rule: the warm pass must not convert the timed
    pass into a hit workload)."""
    import numpy as np
    plens = [dp_len if i % 2 else max(dp_len // 2, 1)
             for i in range(2 * db)]
    rngp = np.random.default_rng(2)
    prompts = [rngp.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in plens]
    return _engine_tier(params, cfg, db, dnew, dp_len + dnew, on_tpu,
                        lambda: prompts, kv_cache_dtype=kv_cache_dtype,
                        weight_bits=weight_bits,
                        enable_prefix_cache=False)[0]


def prefix_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                       kv_cache_dtype=None):
    """The decode_prefix_tokens_per_sec measurement, shared by measure()
    and tools/decode_bench.py so the two sources stay comparable.

    Shared-SYSTEM-PROMPT workload: every request carries the same long
    prefix (3/4 of the prompt) plus a short unique suffix, through the
    same :func:`_engine_tier` scaffold as the paged tier — the prefix
    cache maps the shared pages into each admission after the first
    (the warm pass seeds the trie), and chunked prefill (one page-pair
    per chunk) bounds the per-step stall. The delta vs
    decode_paged_tokens_per_sec at the same lengths IS the
    prefix-cache + chunked-prefill win (hit rate x prefill FLOPs)."""
    import numpy as np
    page = 16 if on_tpu else 8
    sys_len = min(max(page, (dp_len * 3 // 4 // page) * page), dp_len)
    rngp = np.random.default_rng(3)
    sys_prompt = rngp.integers(0, cfg.vocab_size, (sys_len,)).astype(
        np.int32)
    # prompts stay dp_len total so the tier is length-comparable with
    # decode_paged; a zero-length unique suffix (tiny CPU smoke shapes)
    # degenerates to identical prompts — still a valid hit workload.
    # Suffixes REGENERATE per pass: only the system prefix may hit the
    # warm trie, otherwise the timed pass measures full-prompt
    # recaching instead of the documented shared-prefix workload
    def make_prompts():
        return [np.concatenate([sys_prompt, rngp.integers(
            0, cfg.vocab_size, (dp_len - sys_len,)).astype(np.int32)])
            for _ in range(2 * db)]
    return _engine_tier(params, cfg, db, dnew, dp_len + dnew, on_tpu,
                        make_prompts, kv_cache_dtype=kv_cache_dtype,
                        prefill_chunk=2 * page)[0]


def sched_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                      kv_cache_dtype=None, overlap_rider=True,
                      durability_rider=True, trace_rider=True):
    """The decode_sched_tokens_per_sec measurement, shared by measure()
    and tools/decode_bench.py so the two sources stay comparable.

    Oversubscribed TWO-PRIORITY bursty workload through the ISSUE 4
    :class:`~paddle_tpu.serving.ServingScheduler`: ``db`` LOW
    long-prompt requests fill every slot first, then a burst of ``db``
    HIGH short-prompt requests lands — each HIGH admission preempts a
    LOW victim (pages evicted back to the pool) and the victim later
    resumes token-identically through the continuation-prefill replay.
    The step planner runs with a real token budget (one decode per
    slot + one two-page chunk), so the number measures the whole
    control plane: planning, preempt/evict/resume churn, and the
    budget-bounded step latency. Returns ``(tokens_per_sec,
    {"p50_step_ms", "p99_step_ms", "preemptions"}, overlap_rider)`` —
    the latency percentiles are the tier's point: FIFO has no bound on
    them. Prefix cache OFF (same reason as the paged tier: the warm
    pass must not convert the timed pass into a hit workload).

    The overlap rider (ISSUE 12) re-runs the IDENTICAL workload with
    the double-buffered scheduler (``overlap=True`` — expire/admit/
    plan hidden under the in-flight decode step, one commit fence per
    step) and reports {sync_step_ms, overlapped_step_ms,
    host_overhead_fraction (both modes), speedup} — the direct
    measurement of how much host plane the overlap hides at this
    geometry. Best-effort: an overlapped-path failure leaves the
    baseline number standing with the rider None."""
    import numpy as np
    from paddle_tpu.inference.predictor import ContinuousBatchingEngine
    from paddle_tpu.serving import Priority, ServingScheduler
    page = 16 if on_tpu else 8

    def build(overlap):
        eng = ContinuousBatchingEngine(
            params, cfg, max_batch=db, page_size=page,
            max_len=dp_len + dnew, kv_cache_dtype=kv_cache_dtype,
            enable_prefix_cache=False, overlap=overlap)
        return ServingScheduler(eng, token_budget=db + 2 * page,
                                overlap=overlap)

    def one_pass(sched, rngp):
        def mk(n):
            return rngp.integers(0, cfg.vocab_size, (n,)).astype(
                np.int32)
        lows = [sched.submit(mk(dp_len), max_new_tokens=dnew,
                             priority=Priority.LOW) for _ in range(db)]
        # let the LOW wave occupy every slot before the burst
        for _ in range(4):
            sched.step()
        highs = [sched.submit(mk(max(dp_len // 2, 1)),
                              max_new_tokens=max(dnew // 2, 1),
                              priority=Priority.HIGH)
                 for _ in range(db)]
        lats = []
        while True:
            t0 = time.perf_counter()
            more = sched.step()
            lats.append(time.perf_counter() - t0)
            if not more:
                break
        sched.flush()                   # overlap: drain the last step
        return (sum(len(r.tokens) for r in lows + highs), lats)

    def measure(sched):
        # fresh generator per mode: the sync baseline and the overlap
        # rider must replay the IDENTICAL warm+timed prompt stream, or
        # the speedup would compare two different request sets
        rngp = np.random.default_rng(5)
        one_pass(sched, rngp)                           # compile/warm
        p0 = sched.preemptions_total
        t0 = time.perf_counter()
        toks_out, lats = one_pass(sched, rngp)          # steady state
        tps = round(toks_out / (time.perf_counter() - t0), 2)
        return tps, lats, sched.preemptions_total - p0

    sched = build(False)
    tps, lats, preempts = measure(sched)
    lat = {
        "p50_step_ms": round(float(np.percentile(lats, 50)) * 1e3, 3),
        "p99_step_ms": round(float(np.percentile(lats, 99)) * 1e3, 3),
        "preemptions": preempts,
    }
    rider = None
    if overlap_rider:
        try:
            sched_ov = build(True)
            ov_tps, ov_lats, _ = measure(sched_ov)
            rider = {
                "sync_step_ms": lat["p50_step_ms"],
                "overlapped_step_ms": round(
                    float(np.percentile(ov_lats, 50)) * 1e3, 3),
                "host_overhead_fraction": {
                    "sync": round(sched.host_frac_ema, 4),
                    "overlap": round(sched_ov.host_frac_ema, 4)},
                "speedup": round(ov_tps / tps, 3) if tps else None,
            }
        except Exception as e:
            print(f"overlap sched rider failed: {type(e).__name__}: "
                  f"{e}"[:300], file=sys.stderr)
    durability = None
    if durability_rider:
        try:
            durability = _durability_rider(
                params, cfg, db, dp_len, dnew, page,
                kv_cache_dtype=kv_cache_dtype)
        except Exception as e:
            print(f"durability sched rider failed: "
                  f"{type(e).__name__}: {e}"[:300], file=sys.stderr)
    trace = None
    if trace_rider:
        # decode_trace_overhead (ISSUE 16): the IDENTICAL two-wave
        # workload with request tracing ON — every span-close site
        # live on every step — against the baseline above. The
        # zero-cost-when-disabled contract makes the off number the
        # plain run; the rider prices the on switch.
        try:
            from paddle_tpu.observability import tracing as _tracing
            sched_tr = build(False)
            _tracing.enable()
            try:
                _, tr_lats, _ = measure(sched_tr)
            finally:
                _tracing.disable()
            tr_p50 = round(float(np.percentile(tr_lats, 50)) * 1e3, 3)
            off = lat["p50_step_ms"]
            trace = {
                "tracing_off_step_ms": off,
                "tracing_on_step_ms": tr_p50,
                "overhead_frac": (round(tr_p50 / off - 1.0, 4)
                                  if off else None),
            }
        except Exception as e:
            print(f"trace sched rider failed: {type(e).__name__}: "
                  f"{e}"[:300], file=sys.stderr)
    return tps, lat, rider, durability, trace


def _durability_rider(params, cfg, db, dp_len, dnew, page,
                      kv_cache_dtype=None):
    """The decode_durability_overhead rider (ISSUE 15): the sched
    tier's two-wave preemption workload re-run through an
    :class:`~paddle_tpu.serving.EngineSupervisor` with the durable
    journal OFF (in-memory only — the baseline), then with the on-disk
    WAL at each fsync rung (``group`` — the default group-commit
    window — and ``commit`` — fsync every append). Reports
    ``{fsync_policy, wal_ms_per_step, steps_per_sec, overhead_frac}``
    — the measured durability tax next to the PERF_NOTES
    bytes/record · records/step amortization model. The headline gate:
    group-commit overhead < 5% at the CPU smoke geometry."""
    import shutil
    import tempfile

    import numpy as np
    from paddle_tpu.inference.predictor import ContinuousBatchingEngine
    from paddle_tpu.serving import EngineSupervisor, Priority

    def factory():
        return ContinuousBatchingEngine(
            params, cfg, max_batch=db, page_size=page,
            max_len=dp_len + dnew, kv_cache_dtype=kv_cache_dtype,
            enable_prefix_cache=False)

    root = tempfile.mkdtemp(prefix="bench_wal_")

    def run_mode(mode):
        kw = {}
        if mode != "journal_off":
            kw = dict(wal_dir=os.path.join(root, mode),
                      wal_fsync=mode, checkpoint_every=64)
        rngp = np.random.default_rng(5)

        def mk(n):
            return rngp.integers(0, cfg.vocab_size, (n,)).astype(
                np.int32)

        def one_pass(sup):
            reqs = [sup.submit(mk(dp_len), max_new_tokens=dnew,
                               priority=Priority.LOW)
                    for _ in range(db)]
            for _ in range(4):
                sup.step()
            reqs += [sup.submit(mk(max(dp_len // 2, 1)),
                                max_new_tokens=max(dnew // 2, 1),
                                priority=Priority.HIGH)
                     for _ in range(db)]
            s0 = sup.steps_total
            sup.run()
            return (sum(len(r.tokens) for r in reqs),
                    sup.steps_total - s0 + 4)
        sup = EngineSupervisor(factory, token_budget=db + 2 * page,
                               **kw)
        one_pass(sup)                           # compile/warm
        rates, wal_ms = [], []
        for _ in range(3):                      # median beats CPU noise
            w0 = (sup.wal.append_ns + sup.wal.fsync_ns
                  if sup.wal is not None else 0)
            s0 = sup.steps_total
            t0 = time.perf_counter()
            _toks, steps = one_pass(sup)
            dt = time.perf_counter() - t0
            if dt and steps:
                rates.append(steps / dt)
            if sup.wal is not None:
                wal_ms.append(
                    (sup.wal.append_ns + sup.wal.fsync_ns - w0) / 1e6
                    / max(1, sup.steps_total - s0))
        return {"steps_per_sec": (float(np.median(rates))
                                  if rates else None),
                "wal_ms_per_step": (float(np.median(wal_ms))
                                    if wal_ms else None)}
    try:
        base = run_mode("journal_off")
        group = run_mode("group")
        commit = run_mode("commit")
    finally:
        shutil.rmtree(root, ignore_errors=True)

    def overhead(m):
        b, w = base["steps_per_sec"], m["steps_per_sec"]
        return round(1.0 - w / b, 4) if b and w else None
    # the end-to-end ratio is noisy at smoke step times (~2 ms);
    # wal_frac_of_step is the DIRECT measurement — WAL append+fsync ms
    # over the measured step period — and is the honest < 5% headline
    wal_frac = None
    if group["wal_ms_per_step"] and group["steps_per_sec"]:
        wal_frac = round(group["wal_ms_per_step"]
                         / (1000.0 / group["steps_per_sec"]), 4)
    return {
        "fsync_policy": "group",
        "wal_ms_per_step": round(group["wal_ms_per_step"] or 0, 4),
        "wal_frac_of_step": wal_frac,
        "steps_per_sec": {
            "journal_off": round(base["steps_per_sec"], 2),
            "group": round(group["steps_per_sec"], 2),
            "commit": round(commit["steps_per_sec"], 2)},
        "overhead_frac": {"group": overhead(group),
                          "commit": overhead(commit)},
    }


def spec_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                     kv_cache_dtype=None):
    """The decode_spec_tokens_per_sec measurement, shared by measure()
    and tools/decode_bench.py so the two sources stay comparable.

    The paged-engine workload with SPECULATIVE decoding on (ISSUE 5):
    n-gram prompt-lookup drafting + the batched greedy verify program,
    over REPETITIVE prompts (a tiled motif behind a unique head token)
    — the proposer needs in-context repetition to draft from, which is
    exactly the workload speculation targets (templated serving
    traffic, code, structured extraction). Rides the same
    :func:`_engine_tier` scaffold as the paged/prefix tiers (identical
    oversubscription and token accounting, so the delta vs
    decode_paged IS the speculation win), snapshotting the speculation
    counters after the warm pass so the record reflects the timed pass
    only. Returns ``(tokens_per_sec, {"acceptance_rate", "drafted",
    "accepted"})`` — the throughput number only means something next
    to the acceptance rate that produced it, so they ride the record
    together. Prefix cache OFF (same reason as the paged tier: the
    warm pass must not convert the timed pass into a hit workload)."""
    import numpy as np
    rngp = np.random.default_rng(7)
    motif = rngp.integers(0, cfg.vocab_size,
                          (max(dp_len // 8, 1),)).astype(np.int32)

    def make_prompts():
        # unique head so rows aren't identical; the motif repeats so the
        # last n-gram has prior in-context occurrences to look up
        reps = -(-dp_len // motif.size) + 1
        return [np.concatenate([
            rngp.integers(0, cfg.vocab_size, (1,)).astype(np.int32),
            np.tile(motif, reps)[:dp_len - 1]]) for _ in range(2 * db)]

    warm = {}

    def snapshot(eng):
        warm.update(d=eng.spec.drafted_total, a=eng.spec.accepted_total)

    tps, eng = _engine_tier(params, cfg, db, dnew, dp_len + dnew,
                            on_tpu, make_prompts,
                            between_passes=snapshot,
                            kv_cache_dtype=kv_cache_dtype,
                            enable_prefix_cache=False, spec_k=4)
    drafted = eng.spec.drafted_total - warm["d"]
    accepted = eng.spec.accepted_total - warm["a"]
    rider = {
        "acceptance_rate": round(accepted / drafted, 3) if drafted
        else 0.0,
        "drafted": drafted, "accepted": accepted,
    }
    # sampled-spec rider (ISSUE 14): the SAME workload at
    # temperature>0 through the rejection-sampled verify commit — the
    # acceptance rate under min(1, p/q) is the realized 1+k·rate
    # multiplier for sampled traffic, the restriction this PR lifts.
    # Best-effort: a failure leaves the greedy tier standing.
    try:
        warm_s = {}

        def snap_s(e):
            warm_s.update(d=e.spec.drafted_total,
                          a=e.spec.accepted_total)

        tps_s, eng_s = _engine_tier(
            params, cfg, db, dnew, dp_len + dnew, on_tpu,
            make_prompts, between_passes=snap_s,
            kv_cache_dtype=kv_cache_dtype, enable_prefix_cache=False,
            spec_k=4, temperature=0.7)
        d_s = eng_s.spec.drafted_total - warm_s["d"]
        a_s = eng_s.spec.accepted_total - warm_s["a"]
        rider["sampled"] = {
            "temperature": 0.7,
            "tokens_per_sec": tps_s,
            "acceptance_rate": round(a_s / d_s, 3) if d_s else 0.0,
            "drafted": d_s, "accepted": a_s,
        }
    except Exception as e:
        print(f"sampled-spec rider failed: {type(e).__name__}: "
              f"{e}"[:300], file=sys.stderr)
    # non-repetitive scoreboard (ISSUE 20): the SAME geometry over the
    # synth_trace TEXT-mode workload — prompts sampled without
    # replacement, so in-context n-gram lookup finds nothing to draft
    # from by construction. The n-gram proposer's acceptance collapses
    # to ~0 there; the model-based draft path (truncated-layer draft
    # model on the aligned bench target) stays > 0.3 — the number that
    # justifies shipping a draft model at all. Best-effort like the
    # sampled rider.
    try:
        prompts_nr = _text_prompts(cfg, db, dp_len)

        def accept_on(p, **ekw):
            w = {}

            def snap(e):
                w.update(d=e.spec.drafted_total, a=e.spec.accepted_total)

            _, e = _engine_tier(p, cfg, db, dnew, dp_len + dnew,
                                on_tpu, lambda: prompts_nr,
                                between_passes=snap,
                                kv_cache_dtype=kv_cache_dtype,
                                enable_prefix_cache=False, **ekw)
            d = e.spec.drafted_total - w["d"]
            a = e.spec.accepted_total - w["a"]
            return round(a / d, 3) if d else 0.0

        dl = max(1, cfg.num_layers // 2)
        rider["nonrepetitive"] = {
            "ngram_acceptance": accept_on(params, spec_k=4),
            "draft_acceptance": accept_on(
                _align_draft_params(params, dl), spec_k=4,
                draft_layers=dl),
            "draft_layers": dl,
        }
    except Exception as e:
        print(f"nonrepetitive-spec rider failed: {type(e).__name__}: "
              f"{e}"[:300], file=sys.stderr)
    return tps, rider


def _text_prompts(cfg, db, dp_len):
    """2*db NON-repetitive prompts off a ``synth_trace`` text-mode
    trace (ISSUE 20): Zipf marginals, zero in-context token repetition,
    prefix+tail sized to land near ``dp_len`` (shrunk if the model's
    vocab can't cover that many distinct tokens per prompt)."""
    import numpy as np
    from paddle_tpu.serving.traffic import synth_trace
    page = 8
    plen = min(max(page, dp_len // 2 // page * page),
               (cfg.vocab_size - 3) // 2 // page * page)
    tail_hi = min(max(2, dp_len - plen), cfg.vocab_size - 3 - plen)
    trace = synth_trace(11, duration_s=4.0, base_rps=max(6.0, db),
                        page_size=page, prefix_pages=plen // page,
                        vocab=cfg.vocab_size,
                        tail_tokens=(max(1, tail_hi // 2), tail_hi),
                        text=True)
    if not trace:
        raise RuntimeError("text trace came back empty")
    return [trace[i % len(trace)].prompt for i in range(2 * db)]


def _align_draft_params(params, draft_layers, damp=1e-3):
    """Bench-model surgery for the draft/tree tiers (ISSUE 20): damp
    the POST-draft layers' residual output projections so the
    truncated-layer draft is a faithful small model of the bench
    target. The bench weights are near-random (a few train steps), so
    an UN-aligned truncation would measure draft quality of noise —
    the tier measures the speculation MACHINERY (propose/verify/commit
    mechanics and their cost), and alignment is what gives the
    acceptance-rate scoreboard signal, the same way the repetitive
    motif gives the n-gram tier signal. Deployments bring their own
    distilled draft; the rider records the alignment so the record is
    honest."""
    layers = dict(params["layers"])
    for n in ("wo", "wd"):
        layers[n] = layers[n].at[draft_layers:].multiply(damp)
    out = dict(params)
    out["layers"] = layers
    return out


def treespec_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                         kv_cache_dtype=None, tree=(2, 4)):
    """The decode_treespec_tokens_per_sec measurement (ISSUE 20),
    shared by measure() and tools/decode_bench.py so the two sources
    stay comparable.

    Model-based DRAFT + TREE speculation on the paged engine over the
    NON-repetitive text-mode workload (the traffic n-gram lookup can't
    draft from): a truncated-layer shared-embedding draft model
    proposes a (width, depth) token tree per row, the whole tree
    verifies in ONE forward through the tree-masked flash path, and
    the longest accepted root path commits. Same :func:`_engine_tier`
    scaffold as the other serving tiers (so the delta vs decode_spec
    on this trace IS the tree+draft win); the bench target is
    deep-damped so the truncated draft aligns (see
    :func:`_align_draft_params`). Returns ``(tokens_per_sec,
    {"tree_width", "depth", "mean_accepted_path", ...})`` — the
    throughput only means something next to the realized path length,
    so they ride together."""
    w, d = tree
    draft_layers = max(1, cfg.num_layers // 2)
    bench_params = _align_draft_params(params, draft_layers)
    prompts = _text_prompts(cfg, db, dp_len)
    warm = {}

    def snapshot(eng):
        warm.update(d=eng.spec.drafted_total, a=eng.spec.accepted_total,
                    v=eng.spec.verify_steps)

    tps, eng = _engine_tier(bench_params, cfg, db, dnew, dp_len + dnew,
                            on_tpu, lambda: prompts,
                            between_passes=snapshot,
                            kv_cache_dtype=kv_cache_dtype,
                            enable_prefix_cache=False,
                            draft_layers=draft_layers, spec_tree=tree)
    drafted = eng.spec.drafted_total - warm["d"]
    accepted = eng.spec.accepted_total - warm["a"]
    verifies = eng.spec.verify_steps - warm["v"]
    rider = {
        "tree_width": w, "depth": d, "draft_layers": draft_layers,
        # committed tokens per verify (accepted path nodes + bonus):
        # the realized step-compression factor of the tree
        "mean_accepted_path": (round(1.0 + accepted / verifies, 3)
                               if verifies else None),
        "acceptance_rate": round(accepted / drafted, 3) if drafted
        else 0.0,
        "drafted": drafted, "accepted": accepted,
    }
    return tps, rider


def multilora_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                          kv_cache_dtype=None, adapters=6, slots=None,
                          rank=8):
    """The decode_multilora_tokens_per_sec measurement (ISSUE 14),
    shared by measure() and tools/decode_bench.py so the two sources
    stay comparable.

    MANY-TENANT mixed-adapter workload: ``adapters`` LoRA variants
    (rank ``rank``) over a pool of FEWER slots (``slots``, default
    ``adapters - 2``) so the steady state churns — slot hits for hot
    adapters, LRU demotions to the host store and promotions back for
    the tail. Requests cycle through the variant population (plus the
    id-0 base rows every engine serves for free), same mixed-length /
    oversubscription scaffold as the paged tier. The headline is the
    multi-tenant engine's throughput; the baseline it is judged
    against is the SINGLE-MERGED-MODEL engine (one adapter dense-
    merged into the weights, plain engine — the status-quo deployment
    that can only serve ONE variant), whose ratio rides as
    ``vs_single_merged``. Returns ``(tokens_per_sec,
    {"distinct_adapters", "slot_hits", "promote_count", ...})`` — the
    ``decode_multilora_density`` rider: throughput only means
    something next to how much adapter traffic the pool absorbed."""
    import numpy as np
    from paddle_tpu.serving.adapters import (AdapterRegistry, init_lora,
                                             merge_lora)
    from paddle_tpu.serving import HostPageStore
    slots = slots if slots is not None else max(adapters - 2, 1)
    registry = AdapterRegistry(cfg)
    for aid in range(1, adapters + 1):
        registry.register(aid, init_lora(cfg, rank, seed=300 + aid))
    plens = [dp_len if i % 2 else max(dp_len // 2, 1)
             for i in range(2 * db)]
    rngp = np.random.default_rng(17)
    prompts = [rngp.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in plens]

    # single-merged-model baseline: adapter 1 dense-merged, plain
    # engine — measured FIRST so a multilora failure can't orphan it
    merged = merge_lora(params, cfg, registry.get(1))
    base_tps, _ = _engine_tier(merged, cfg, db, dnew, dp_len + dnew,
                               on_tpu, lambda: prompts,
                               kv_cache_dtype=kv_cache_dtype,
                               enable_prefix_cache=False)

    pool_kw = dict(slots=slots, rank=rank, registry=registry,
                   store=HostPageStore(page_size=16 if on_tpu else 8))
    warm = {}

    def snapshot(eng):
        st = eng.adapters.stats()
        warm.update(h=st["adapter_slot_hits_total"],
                    p=st["adapter_promotions_total"],
                    d=st["adapter_demotions_total"])

    tps, eng = _engine_tier(
        params, cfg, db, dnew, dp_len + dnew, on_tpu,
        lambda: prompts, between_passes=snapshot,
        kv_cache_dtype=kv_cache_dtype, enable_prefix_cache=False,
        adapters=pool_kw,
        # request i serves variant (i mod (adapters+1)): id 0 = base
        per_request_kw=lambda i: {"adapter_id": i % (adapters + 1)})
    st = eng.adapters.stats()
    return tps, {
        "distinct_adapters": adapters,
        "pool_slots": slots,
        "rank": rank,
        "slot_hits": st["adapter_slot_hits_total"] - warm["h"],
        "promote_count": st["adapter_promotions_total"] - warm["p"],
        "demote_count": st["adapter_demotions_total"] - warm["d"],
        "vs_single_merged": (round(tps / base_tps, 3) if base_tps
                             else None),
        "single_merged_tokens_per_sec": base_tps,
    }


def tp_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                   kv_cache_dtype=None, tp=4):
    """The decode_tp_tokens_per_sec measurement, shared by measure()
    and tools/decode_bench.py so the two sources stay comparable.

    The paged-engine MIXED-LENGTH workload (same mix/oversubscription
    as decode_paged — the tier it is deltaed against) on a
    TENSOR-PARALLEL tp=4 serving mesh (ISSUE 7): weights partitioned by
    the regex rules, page pools sharded on the kv-head axis, the
    decode/chunk programs lowered through shard_map with exact
    all-gathers. The ratio vs decode_paged at the same lengths IS the
    tp aggregate-vs-single-chip scaling factor and rides the record as
    ``decode_tp_scaling``. Needs >= tp devices: a single-chip tunnel
    run raises (and the tier stays null with honest provenance) —
    multi-chip slices and the 8-device host-platform CI measure it."""
    import numpy as np
    import jax
    from paddle_tpu.distributed.mesh import serving_mesh
    ndev = len(jax.devices())
    if ndev < tp:
        raise RuntimeError(
            f"decode_tp tier needs a {tp}-device mesh, found {ndev} "
            f"device(s) — run on a multi-chip slice (or the host-"
            f"platform 8-device CI mesh)")
    plens = [dp_len if i % 2 else max(dp_len // 2, 1)
             for i in range(2 * db)]
    rngp = np.random.default_rng(11)
    prompts = [rngp.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in plens]
    return _engine_tier(params, cfg, db, dnew, dp_len + dnew, on_tpu,
                        lambda: prompts, kv_cache_dtype=kv_cache_dtype,
                        enable_prefix_cache=False,
                        mesh=serving_mesh(tp))[0]


def tp2d_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                     kv_cache_dtype=None, tp=2, dp=2):
    """The decode_tp2d_tokens_per_sec measurement, shared by measure()
    and tools/decode_bench.py so the two sources stay comparable.

    The same MIXED-LENGTH paged workload as the 1-D tp tier, on a 2-D
    ``tp x dp`` serving mesh (ISSUE 17): weights column-sharded over
    tp exactly as before, page pools head-sharded on tp and REPLICATED
    across dp, and the decode batch SPLIT over dp — ``db`` rows per dp
    shard, so ``max_batch = db * dp`` rows advance per step through
    the same per-shard program geometry the 1-D tier runs. The ratio
    vs the 1-D tp tier is the dp batch-scaling factor and rides the
    record as ``decode_tp2d_scaling``. Needs >= tp*dp devices: a
    single-chip tunnel run raises (tier stays null with honest
    provenance) — multi-chip slices and the 8-device host-platform CI
    mesh measure it."""
    import numpy as np
    import jax
    from paddle_tpu.distributed.mesh import serving_mesh
    ndev = len(jax.devices())
    if ndev < tp * dp:
        raise RuntimeError(
            f"decode_tp2d tier needs a {tp}x{dp}-device mesh, found "
            f"{ndev} device(s) — run on a multi-chip slice (or the "
            f"host-platform 8-device CI mesh)")
    rows = db * dp
    plens = [dp_len if i % 2 else max(dp_len // 2, 1)
             for i in range(2 * rows)]
    rngp = np.random.default_rng(17)
    prompts = [rngp.integers(0, cfg.vocab_size, (n,)).astype(np.int32)
               for n in plens]
    return _engine_tier(params, cfg, rows, dnew, dp_len + dnew, on_tpu,
                        lambda: prompts, kv_cache_dtype=kv_cache_dtype,
                        enable_prefix_cache=False,
                        mesh=serving_mesh(tp, dp))[0]


def cluster_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                        kv_cache_dtype=None, replicas=2):
    """The decode_cluster_tokens_per_sec measurement, shared by
    measure() and tools/decode_bench.py so the two sources stay
    comparable.

    TWO engine replicas behind the ISSUE 9
    :class:`~paddle_tpu.serving.ServingCluster` router, serving a
    shared-prefix TENANT workload: one tenant per replica, each with
    its own system prompt (3/4 of the prompt, page-aligned) plus
    per-request unique suffixes — prefix-affinity routing pins each
    tenant to the replica whose trie holds its system prompt, so the
    cluster converts the tenant mix into per-replica prefix-hit
    workloads instead of thrashing every trie with every tenant.
    Suffixes REGENERATE per pass (only the system prefix may hit the
    warm trie, same rule as the prefix tier). The rider is the
    cluster's honest headline: the SAME request set through ONE engine
    (same geometry, prefix cache on), with the cluster-vs-single-engine
    ratio riding the record as ``decode_cluster_scaling`` — on one
    host the replicas timeshare the chip, so the ratio measures router
    + handoff overhead; on a multi-chip deployment each replica owns
    its silicon and the ratio is the scaling win. Returns
    ``(tokens_per_sec, {"replicas", "vs_single_engine",
    "affinity_hit_rate"})``."""
    import numpy as np
    from paddle_tpu.inference.predictor import ContinuousBatchingEngine
    from paddle_tpu.serving import ServingCluster
    page = 16 if on_tpu else 8
    sys_len = min(max(page, (dp_len * 3 // 4 // page) * page), dp_len)
    rngp = np.random.default_rng(13)
    sys_prompts = [rngp.integers(0, cfg.vocab_size, (sys_len,)).astype(
        np.int32) for _ in range(replicas)]

    def make_jobs():
        jobs = []
        for t in range(replicas):
            for _ in range(2 * db):
                jobs.append((t, np.concatenate([
                    sys_prompts[t],
                    rngp.integers(0, cfg.vocab_size,
                                  (dp_len - sys_len,)).astype(
                                      np.int32)])))
        return jobs

    def engine():
        return ContinuousBatchingEngine(
            params, cfg, max_batch=db, page_size=page,
            max_len=dp_len + dnew, kv_cache_dtype=kv_cache_dtype)

    single = engine()      # persistent, like the cluster's replicas —
    # both sides' warm pass absorbs compiles and seeds the tries

    def run_single():
        reqs = [single.submit(p, max_new_tokens=dnew)
                for _, p in make_jobs()]
        single.run()
        return sum(r.max_new_tokens for r in reqs)

    run_single()                                    # compile/warm pass
    t0 = time.perf_counter()
    toks = run_single()
    single_tps = toks / (time.perf_counter() - t0)

    cluster = ServingCluster(engine, replicas=replicas)

    def run_cluster():
        reqs = [cluster.submit(p, max_new_tokens=dnew,
                               tenant=f"tenant{t}")
                for t, p in make_jobs()]
        cluster.run()
        return sum(r.max_new_tokens for r in reqs)

    run_cluster()                                   # warm (binds affinity)
    t0 = time.perf_counter()
    toks = run_cluster()
    tps = round(toks / (time.perf_counter() - t0), 2)
    scaling = {
        "replicas": replicas,
        "vs_single_engine": round(tps / single_tps, 3) if single_tps
        else None,
        "affinity_hit_rate": round(
            cluster.router.stats()["affinity_hit_rate"], 3),
    }
    # overlap sub-rider (ISSUE 12): the same tenant workload with every
    # supervised replica running the double-buffered scheduler —
    # best-effort, the sync number stands either way
    try:
        cl_ov = ServingCluster(engine, replicas=replicas, overlap=True)

        def run_ov():
            reqs = [cl_ov.submit(p, max_new_tokens=dnew,
                                 tenant=f"tenant{t}")
                    for t, p in make_jobs()]
            cl_ov.run()
            return sum(r.max_new_tokens for r in reqs)

        run_ov()                                    # warm
        t0 = time.perf_counter()
        toks = run_ov()
        ov_tps = round(toks / (time.perf_counter() - t0), 2)
        scaling["overlap"] = {
            "tokens_per_sec": ov_tps,
            "vs_sync": round(ov_tps / tps, 3) if tps else None,
        }
    except Exception as e:
        print(f"overlap cluster rider failed: {type(e).__name__}: "
              f"{e}"[:300], file=sys.stderr)
    return tps, scaling


def multiproc_overhead_tier(on_tpu, replicas=2):
    """The ``decode_multiproc_overhead`` rider (ISSUE 19), shared by
    measure() and tools/decode_bench.py so the two sources stay
    comparable.

    The cluster tier's disaggregated shape (one prefill + one decode
    replica) as a real PROCESS TREE behind the socket RPC control
    plane, priced against the identical shape in-process. Workers
    build their own engines from the spawn-stable tiny factory
    (bit-identical params from the seed), and the controller-side
    stubs are wrapped with a wall-clock accumulator, so the rider
    measures the CONTROL PLANE and not the model: ``rpc_ms_per_step``
    is total controller-side RPC wall per cluster step (the step
    fan-out plus load_stats/handoff probes), ``handoff_wire_ms`` the
    mean wall cost of moving one prefilled session across the process
    boundary (export_prefilled + adopt_prefilled, CRC-gated KV payload
    included), and ``vs_in_process`` the multiproc/in-process
    throughput ratio on the same request set — the per-host price of
    process isolation (PERF_NOTES has the frame-bytes cost model; on a
    multi-host deployment the same frames buy kill -9 survival, which
    one process can never offer). Workers are pinned to CPU: the tiny
    model is host-latency-bound either way, and a TPU-owning bench
    process must not share the chip lock with its children."""
    import numpy as np
    import shutil
    import tempfile
    from paddle_tpu.serving.cluster import ServingCluster
    from paddle_tpu.serving.multiproc import MultiProcessCluster
    from paddle_tpu.serving.node import tiny_llama_engine

    rngp = np.random.RandomState(11)
    sys_prompt = rngp.randint(3, 256, (12,)).astype(np.int32)

    def make_jobs():
        # shared system prefix + unique tails, regenerated per pass —
        # same discipline as the in-process cluster tier above
        jobs = []
        for _ in range(3 * replicas):
            tail = rngp.randint(3, 256,
                                (int(rngp.randint(2, 7)),)).astype(
                                    np.int32)
            jobs.append((np.concatenate([sys_prompt, tail]),
                         int(rngp.randint(3, 6))))
        return jobs

    def run_pass(cluster):
        handles = [cluster.submit(p, max_new_tokens=m)
                   for p, m in make_jobs()]
        steps = 0
        while cluster.step():
            steps += 1
        return sum(len(h.tokens) for h in handles), steps

    inproc = ServingCluster(tiny_llama_engine(), replicas=replicas,
                            prefill_replicas=1,
                            supervisor_kw=dict(sleep=lambda s: None,
                                               backoff_s=0.0))
    run_pass(inproc)                                # compile/warm pass
    t0 = time.perf_counter()
    toks, _ = run_pass(inproc)
    in_tps = toks / (time.perf_counter() - t0)

    acc = {"rpc_ns": 0, "handoff_ns": 0, "exports": 0}

    def _instrument(node):
        orig = node.call

        def timed(method, data=None, blobs=None, **kw):
            t0 = time.perf_counter_ns()
            try:
                return orig(method, data, blobs, **kw)
            finally:
                dt = time.perf_counter_ns() - t0
                acc["rpc_ns"] += dt
                if method in ("export_prefilled", "adopt_prefilled"):
                    acc["handoff_ns"] += dt
                    if method == "export_prefilled":
                        acc["exports"] += 1
        node.call = timed

    wd = tempfile.mkdtemp(prefix="ptpu_mpbench_")
    mc = MultiProcessCluster(
        replicas=replicas, prefill_replicas=1, workdir=wd,
        xla_cache_dir=_XLA_CACHE_DIR,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))
    try:
        for node in mc.nodes:
            _instrument(node)
        run_pass(mc)                                # workers compile
        base = dict(acc)
        t0 = time.perf_counter()
        toks, steps = run_pass(mc)
        dt = time.perf_counter() - t0
        mp_tps = toks / dt
        rpc_ns = acc["rpc_ns"] - base["rpc_ns"]
        handoff_ns = acc["handoff_ns"] - base["handoff_ns"]
        exports = acc["exports"] - base["exports"]
    finally:
        mc.close()
        shutil.rmtree(wd, ignore_errors=True)
    return {
        "replicas": replicas,
        "tokens_per_sec": round(mp_tps, 2),
        "rpc_ms_per_step": (round(rpc_ns / steps / 1e6, 3)
                            if steps else None),
        "handoff_wire_ms": (round(handoff_ns / exports / 1e6, 3)
                            if exports else None),
        "vs_in_process": round(mp_tps / in_tps, 3) if in_tps else None,
    }


def offload_decode_tier(params, cfg, db, dp_len, dnew, on_tpu,
                        kv_cache_dtype=None):
    """The decode_offload_tokens_per_sec measurement, shared by
    measure() and tools/decode_bench.py so the two sources stay
    comparable.

    The ISSUE 4 scheduler tier's oversubscribed TWO-PRIORITY bursty
    workload (LOW long-prompt wave fills every slot, then a HIGH burst
    preempts its way in) with the ISSUE 10 HOST TIER enabled: every
    preemption victim SWAPS OUT to host RAM and every resume SWAPS IN
    by one donated scatter instead of the replay prefill. The rider is
    the tier's honest story: ``swap_in_ms_p50`` (the host→device copy
    that replaced the replay) and ``vs_replay_prefill`` — the same
    workload through the same scheduler with the host tier OFF, so the
    ratio IS the swap-vs-replay win at this geometry (PERF_NOTES has
    the crossover model; on CPU smoke shapes the replay is tiny, so
    the ratio mostly prices the swap machinery's overhead — the TPU
    run is where replay FLOPs dominate). Prefix cache OFF (same rule
    as every engine tier: the warm pass must not convert the timed
    pass into a hit workload; the host store holds only swap
    payloads). Returns ``(tokens_per_sec, {"preemptions", "swap_ins",
    "swap_in_ms_p50", "vs_replay_prefill"})``."""
    import numpy as np
    from paddle_tpu.inference.predictor import ContinuousBatchingEngine
    from paddle_tpu.serving import Priority, ServingScheduler
    page = 16 if on_tpu else 8

    def build(host, overlap=False):
        eng = ContinuousBatchingEngine(
            params, cfg, max_batch=db, page_size=page,
            max_len=dp_len + dnew, kv_cache_dtype=kv_cache_dtype,
            enable_prefix_cache=False, host_tier=host, overlap=overlap)
        return eng, ServingScheduler(eng, token_budget=db + 2 * page)

    def one_pass(sched, rngp):
        def mk(n):
            return rngp.integers(0, cfg.vocab_size, (n,)).astype(
                np.int32)
        lows = [sched.submit(mk(dp_len), max_new_tokens=dnew,
                             priority=Priority.LOW) for _ in range(db)]
        for _ in range(4):
            sched.step()
        highs = [sched.submit(mk(max(dp_len // 2, 1)),
                              max_new_tokens=max(dnew // 2, 1),
                              priority=Priority.HIGH)
                 for _ in range(db)]
        while sched.step():
            pass
        return sum(len(r.tokens) for r in lows + highs)

    # replay baseline: the identical workload, host tier OFF — the
    # rider's denominator (every resume pays the replay prefill)
    # every mode replays the IDENTICAL warm+timed prompt stream (one
    # fresh generator per mode) so the rider ratios compare the same
    # request set, not different draws from a shared stream
    rng = np.random.default_rng(19)
    _, sched_replay = build(False)
    one_pass(sched_replay, rng)                     # compile/warm pass
    t0 = time.perf_counter()
    toks = one_pass(sched_replay, rng)
    replay_tps = toks / (time.perf_counter() - t0)

    rng = np.random.default_rng(19)
    eng, sched = build(True)
    one_pass(sched, rng)                            # warm (shares compiles)
    n0 = len(eng.cache.swap_in_ms)
    si0, p0 = eng.cache.swap_ins_total, sched.preemptions_total
    t0 = time.perf_counter()
    toks = one_pass(sched, rng)
    tps = round(toks / (time.perf_counter() - t0), 2)
    lat = eng.cache.swap_in_ms[n0:]
    rider = {
        "preemptions": sched.preemptions_total - p0,
        "swap_ins": eng.cache.swap_ins_total - si0,
        "swap_in_ms_p50": (round(float(np.percentile(lat, 50)), 3)
                           if lat else None),
        "vs_replay_prefill": (round(tps / replay_tps, 3)
                              if replay_tps else None),
    }
    # overlap sub-rider (ISSUE 12): the same swap-heavy workload with
    # the double-buffered scheduler AND async swap-out DMAs (issued
    # under the in-flight decode, fenced at commit) — best-effort
    try:
        rng = np.random.default_rng(19)
        eng_ov, sched_ov = build(True, overlap=True)
        one_pass(sched_ov, rng)                     # warm
        t0 = time.perf_counter()
        toks = one_pass(sched_ov, rng)
        ov_tps = round(toks / (time.perf_counter() - t0), 2)
        rider["overlap"] = {
            "tokens_per_sec": ov_tps,
            "vs_sync": round(ov_tps / tps, 3) if tps else None,
            "host_overhead_fraction": round(sched_ov.host_frac_ema, 4),
        }
    except Exception as e:
        print(f"overlap offload rider failed: {type(e).__name__}: "
              f"{e}"[:300], file=sys.stderr)
    return tps, rider


def slo_goodput_tier(params, cfg, db, dp_len, dnew, on_tpu,
                     kv_cache_dtype=None):
    """The decode_slo_goodput_tokens_per_sec measurement (ISSUE 13),
    shared by measure() and tools/decode_bench.py so the two sources
    stay comparable.

    The trace-driven traffic harness against an AUTOSCALING cluster:
    a fixed-seed open-loop trace (tenant prefix families, one 4x burst
    window, mixed priority/deadline/length — see
    :func:`paddle_tpu.serving.traffic.synth_trace`) drives a cluster
    that starts at ONE replica and breathes with load through the
    :class:`~paddle_tpu.serving.ClusterAutoscaler` (scale-up on
    backlog, scale-down after the burst, through the retire_replica
    drain path). The virtual :class:`~paddle_tpu.serving.FakeClock`
    makes arrival dynamics and SLO accounting deterministic; wall time
    prices the actual serving work. The headline is GOODPUT — tokens
    of deadline-met requests per wall second, not raw throughput:
    overload work that misses its SLO counts for nothing, which is
    exactly the regression this tier gates. The rider carries the
    quantities that explain the number: deadline-met fraction, p99
    TTFT (virtual ms), p99 per-token latency, the autoscaler's
    up/down event counts for the timed pass, and the rejection split
    (the admission machinery's visible work)."""
    from paddle_tpu.inference.predictor import ContinuousBatchingEngine
    from paddle_tpu.serving import (ClusterAutoscaler, FakeClock,
                                    ServingCluster, run_trace,
                                    synth_trace)
    page = 16 if on_tpu else 8
    prefix_pages = max(1, (dp_len // 2) // page)
    tail_max = max(2, dp_len // 2)
    # the engine must hold the LONGEST trace prompt plus its decode
    # budget (prefix family + unique tail + new tokens)
    max_len = prefix_pages * page + tail_max + dnew

    def factory():
        return ContinuousBatchingEngine(
            params, cfg, max_batch=db, page_size=page,
            max_len=max_len, kv_cache_dtype=kv_cache_dtype)

    clock = FakeClock()
    cluster = ServingCluster(
        factory, replicas=1, clock=clock,
        autoscaler=ClusterAutoscaler(
            min_replicas=1, max_replicas=3,
            up_backlog_per_replica=2.0 * db,
            down_backlog_per_replica=0.5,
            up_after=1, down_after=4, cooldown_ticks=3),
        supervisor_kw=dict(backoff_s=0.0, sleep=lambda s: None))
    trace = synth_trace(
        seed=29, duration_s=3.0, base_rps=4.0 * db, tenants=3,
        page_size=page, prefix_pages=prefix_pages,
        vocab=cfg.vocab_size, tail_tokens=(1, tail_max),
        new_tokens=(max(1, dnew // 2), dnew),
        burst_mult=4.0, deadline_frac=0.5, deadline_s=(0.5, 2.5))
    run_trace(cluster, trace, clock, step_dt=0.05)  # compile/warm pass
    report = run_trace(cluster, trace, clock, step_dt=0.05)
    rider = {
        "requests": report.requests,
        "deadline_met_fraction": round(report.deadline_met_fraction,
                                       4),
        "p99_ttft_ms": (round(report.p99_ttft_s * 1e3, 1)
                        if report.p99_ttft_s is not None else None),
        "p99_per_token_ms": (
            round(report.p99_per_token_s * 1e3, 3)
            if report.p99_per_token_s is not None else None),
        "autoscale_up": report.autoscale_up,
        "autoscale_down": report.autoscale_down,
        "rejected": dict(report.rejected),
    }
    return round(report.goodput_tokens_per_s, 2), rider


_DECODE_TIERS = ("decode_tokens_per_sec", "decode_int8_tokens_per_sec",
                 "decode_int4_tokens_per_sec", "decode_w8kv8_tokens_per_sec",
                 "decode_paged_tokens_per_sec",
                 "decode_prefix_tokens_per_sec",
                 "decode_sched_tokens_per_sec",
                 "decode_spec_tokens_per_sec",
                 "decode_treespec_tokens_per_sec",
                 "decode_tp_tokens_per_sec",
                 "decode_tp2d_tokens_per_sec",
                 "decode_cluster_tokens_per_sec",
                 "decode_offload_tokens_per_sec",
                 "decode_slo_goodput_tokens_per_sec",
                 "decode_multilora_tokens_per_sec")

# rider dicts that travel with their tier when it carries from an older
# record: the scheduler tier's p50/p99 step-latency bound (ISSUE 4),
# the speculative tier's acceptance rate (ISSUE 5 — the number that
# explains the throughput) and the tp tier's aggregate-vs-single-chip
# scaling factor (ISSUE 7). A carried tier without its rider would drop
# the very quantity the tier reports. tools/tpu_watch.sh merges the
# same pairs on the shell side.
_DECODE_RIDERS = (("decode_sched_tokens_per_sec", "decode_sched_step_ms"),
                  ("decode_sched_tokens_per_sec",
                   "decode_overlap_speedup"),
                  ("decode_sched_tokens_per_sec",
                   "decode_durability_overhead"),
                  ("decode_sched_tokens_per_sec",
                   "decode_trace_overhead"),
                  ("decode_spec_tokens_per_sec", "decode_spec_acceptance"),
                  ("decode_treespec_tokens_per_sec",
                   "decode_treespec_stats"),
                  ("decode_tp_tokens_per_sec", "decode_tp_scaling"),
                  ("decode_tp2d_tokens_per_sec", "decode_tp2d_scaling"),
                  ("decode_cluster_tokens_per_sec",
                   "decode_cluster_scaling"),
                  ("decode_cluster_tokens_per_sec",
                   "decode_multiproc_overhead"),
                  ("decode_offload_tokens_per_sec",
                   "decode_offload_resume"),
                  ("decode_slo_goodput_tokens_per_sec",
                   "decode_slo_metrics"),
                  ("decode_multilora_tokens_per_sec",
                   "decode_multilora_density"),
                  ("decode_paged_tokens_per_sec",
                   "decode_fused_speedup"))


def _label_decode_source(extra: dict, carried_tiers,
                         reason: str = None) -> None:
    """Stamp PER-TIER provenance: ``decode_source`` maps each non-null
    decode tier to ``"live"`` (measured by the run that owns the record)
    or ``"carried"`` (inherited from BENCH_LASTGOOD) — a blanket string
    would misattribute mixed fresh/stale records (ADVICE r5). Only
    written when at least one tier actually carried; absent means every
    present tier is live.

    ``reason`` (ISSUE 8 satellite) additionally records WHY each tier
    carried in ``decode_fallback`` — ``probe_killed`` (the backend
    probe child died/hung, so nothing could be measured),
    ``quick_capture`` (the reduced-rep live fallback banked the
    headline but skipped every decode tier) or ``stale_last_good``
    (the values are simply inherited from the last good record).
    Labels already on a tier are respected, same as decode_source."""
    if not carried_tiers:
        return
    # respect labels already on the record (e.g. a _backfill_decode
    # carry riding into _record_last_good): a tier once marked carried
    # stays carried; only genuinely unlabeled tiers default to live
    prev = extra.get("decode_source")
    prev = prev if isinstance(prev, dict) else {}
    extra["decode_source"] = {
        k: ("carried" if k in carried_tiers else prev.get(k, "live"))
        for k in _DECODE_TIERS if extra.get(k) is not None}
    if reason:
        prev_fb = extra.get("decode_fallback")
        prev_fb = prev_fb if isinstance(prev_fb, dict) else {}
        extra["decode_fallback"] = {
            **{k: v for k, v in prev_fb.items()
               if extra.get(k) is not None},
            **{k: prev_fb.get(k, reason) for k in carried_tiers
               if extra.get(k) is not None}}


def _backfill_decode(rec: dict) -> dict:
    """If this run's decode extras are null but a previous standalone
    decode-bench capture lives in BENCH_LASTGOOD (merged there by
    tools/tpu_watch.sh stage b / _record_last_good carry-forward), carry
    the measured tiers into the emitted record — labeled PER TIER via
    ``decode_source`` ({tier: "live"|"carried"}) so a carried number can
    never masquerade as a same-run measurement. TPU records only; CPU
    smoke stays pure."""
    try:
        if "tpu" not in str(rec.get("extra", {}).get("device", "")).lower():
            return rec
        if rec["extra"].get("decode_tokens_per_sec") is not None:
            return rec
        with open(_LASTGOOD) as f:
            lg = json.load(f)
        lx = lg.get("extra", {})
        carried = set()
        for k in _DECODE_TIERS:
            if rec["extra"].get(k) is None and lx.get(k) is not None:
                rec["extra"][k] = lx[k]
                carried.add(k)
        for tier, rider in _DECODE_RIDERS:
            if (tier in carried and rec["extra"].get(rider) is None
                    and lx.get(rider) is not None):
                rec["extra"][rider] = lx[rider]
        if carried:
            rec["extra"]["decode_carried_from"] = (
                "BENCH_LASTGOOD "
                f"({lx.get('decode_recorded_at') or lg.get('recorded_at')})")
            # WHY the tiers carried: a quick-capture child deliberately
            # skips every decode tier; anything else inherited a
            # plain stale value
            reason = ("quick_capture"
                      if (rec["extra"].get("quick_capture")
                          or os.environ.get("PADDLE_TPU_BENCH_QUICK"))
                      else "stale_last_good")
            _label_decode_source(rec["extra"], carried, reason=reason)
    except Exception:
        pass
    return rec


def _is_oom(exc) -> bool:
    s = f"{type(exc).__name__}: {exc}"
    return ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s or "OOM" in s)


def measure(batch_override: Optional[int] = None, on_headline=None,
            t_start: Optional[float] = None):
    """Measure train throughput, then (budget permitting) decode extras.

    ``on_headline`` is called with the headline result dict as soon as the
    train measurement is known — the child prints it immediately so the
    number survives even if a later decode compile blows the watchdog (the
    parent takes the LAST parseable line; decode extras re-print an
    enriched line).
    """
    import numpy as np
    import jax
    import jax.numpy as jnp
    from paddle_tpu.models import train

    # budget clock: the CHILD's start, not this call's — an OOM-ladder
    # retry must not reset the decode-margin guard's notion of elapsed
    t_measure_start = time.perf_counter() if t_start is None else t_start
    cfg, seq, batch = pick_config()
    seq_chunk = None
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        seq_chunk = 512
        cfg, batch, seq_chunk = _apply_perf_winner(cfg, batch, seq_chunk)
    if batch_override is not None:
        batch = batch_override
    # quick live-capture fallback mode (ROADMAP standing note): a flaky
    # tunnel that failed every health probe often still survives a
    # SHORT window — halve the batch, cut the reps, skip every decode
    # extra, and bank a live (clearly labeled) headline instead of
    # riding stale_last_good for the whole round
    quick = bool(os.environ.get("PADDLE_TPU_BENCH_QUICK"))
    if quick:
        batch = max(1, batch // 2)
    step = train.make_train_step(cfg, seq_chunk=seq_chunk)
    state = jax.jit(lambda k: train.init_train_state(k, cfg))(
        jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq)), jnp.int32)

    # warmup / compile; sync via host transfer (block_until_ready is not a
    # reliable fence through the remote-dispatch tunnel)
    state, m = step(state, tokens)
    float(m["loss"])
    state, m = step(state, tokens)
    float(m["loss"])

    iters = (3 if quick else 10) if on_tpu else 3
    t0 = time.perf_counter()
    for _ in range(iters):
        state, m = step(state, tokens)
    lossv = float(m["loss"])
    dt = (time.perf_counter() - t0) / iters

    toks = batch * seq
    tps = toks / dt
    mfu = tps * cfg.flops_per_token(seq) / peak_flops(jax.devices()[0])
    if quick:
        # label the capture so a reduced-rep/batch number can never
        # masquerade as a full measurement downstream
        r = _result(tps, mfu, seq, batch, cfg, lossv, None)
        r["extra"]["quick_capture"] = True
        return r
    if on_headline is not None:
        on_headline(_result(tps, mfu, seq, batch, cfg, lossv, None))

    # serving path: batched KV-cache decode throughput (reference decode
    # benches run block_multi_head_attention; here the pallas decode
    # kernel). The headline line is already out, so a watchdog kill here
    # only loses the extras — but still leave margin for the enriched
    # line to make it (each decode variant costs ~2 jit compiles).
    decode_tps = None
    budget = int(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "600"))

    def remaining():
        return budget - (time.perf_counter() - t_measure_start)

    # per-phase breakdown (one already-compiled train step + a tiny
    # eager generate) — rides the round JSON under "phases"; captured
    # AFTER the decode tiers normally so it can't starve them, and only
    # here on the skip path when decode is off the table anyway
    if on_tpu and remaining() < 150:
        print(f"decode bench skipped: only {remaining():.0f}s of "
              f"{budget}s budget left", file=sys.stderr)
        phases = (_capture_phases(step, state, tokens, cfg)
                  if remaining() > 75 else None)
        return _result(tps, mfu, seq, batch, cfg, lossv, None,
                       phases=phases)
    try:
        from paddle_tpu.models import generate as gen
        db, dp_len, dnew = (8, 128, 64) if on_tpu else (2, 8, 8)
        prompt = jnp.asarray(np.random.default_rng(1).integers(
            0, cfg.vocab_size, (db, dp_len)), jnp.int32)
        def decode_rate(pp, kv=None):
            """Prefill-subtracted decode tokens/s for a params tree;
            ``kv="int8"`` also quantizes the KV cache (per-row scales,
            in-kernel dequant)."""
            def make(n):
                f = jax.jit(lambda pr: gen.generate(
                    pp, pr, cfg, max_new_tokens=n, temperature=0.0,
                    kv_cache_dtype=kv))
                np.asarray(f(prompt))              # compile + host fence
                return f

            def timed(f):
                best = float("inf")
                for _ in range(3):
                    t0 = time.perf_counter()
                    np.asarray(f(prompt))          # host-transfer fence
                    best = min(best, time.perf_counter() - t0)
                return best
            g_full, g_one = make(dnew), make(1)
            ddt = timed(g_full) - timed(g_one)
            if ddt <= 0:  # tiny CPU smoke configs: noise swamps the delta
                ddt = timed(g_full)
            return round(db * (dnew - 1) / ddt, 2)

        decode_tps = decode_rate(state.params)
    except Exception as e:  # decode bench is auxiliary; never kill the
        # headline number — but say why it's missing (it has come back
        # null on every live run so far)
        print(f"decode bench failed: {type(e).__name__}: {e}"[:500],
              file=sys.stderr)

    # int8 weight-only serving variant (decode is HBM-bound; int8 halves
    # the weight bytes) — only with budget left after the fp decode
    decode_int8_tps = None
    int8_params = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            int8_params = gen.quantize_weights(state.params, cfg)
            decode_int8_tps = decode_rate(int8_params)
        except Exception as e:
            print(f"int8 decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # per-group int4 variant on the PAGED ENGINE (ISSUE 11): quarter
    # weight bytes through the serving tower the cluster actually
    # ships, not the dense generate() path the slot used to alias.
    # Gated on the fp decode baseline only — a dense-int8 failure must
    # not null the paged low-bit slots (the pre-ISSUE-11 outcome)
    decode_int4_tps = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_int4_tps = lowbit_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu, 4)
        except Exception as e:
            print(f"int4 decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # weight-int8 + KV-int8 on the PAGED ENGINE: the serving sweet spot
    # (both weight AND cache HBM traffic halved)
    decode_w8kv8_tps = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_w8kv8_tps = lowbit_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu, 8,
                kv_cache_dtype="int8")
        except Exception as e:
            print(f"w8kv8 decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # paged KV + continuous batching at MIXED request lengths: the
    # serving-engine tier (paddle_tpu/serving + ContinuousBatchingEngine)
    # — throughput includes the host scheduling loop, i.e. what a server
    # actually ships; the fused-kernel speedup rider travels with it
    decode_paged_tps = None
    decode_fused = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_paged_tps, decode_fused = paged_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu,
                fused_rider=not on_tpu or remaining() > 240)
        except Exception as e:
            print(f"paged decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # shared-system-prompt serving: prefix cache + chunked prefill on
    # top of the paged engine — the ISSUE 3 serving-throughput tier
    decode_prefix_tps = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_prefix_tps = prefix_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"prefix decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # SLO-scheduler control plane: oversubscribed two-priority bursty
    # workload (preempt/evict/resume + token-budgeted steps) — the
    # ISSUE 4 tier, with p50/p99 step latency riding the record
    decode_sched = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_sched = sched_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"sched decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # speculative decoding on the paged engine: n-gram draft + batched
    # verify over a repetitive workload — the ISSUE 5 tier, with the
    # acceptance rate riding the record
    decode_spec = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_spec = spec_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"spec decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # model-based draft + tree speculation (ISSUE 20): truncated-layer
    # draft model proposing a token tree per row, one-forward tree
    # verify, over the NON-repetitive text-mode trace the n-gram
    # proposer can't draft from — throughput + the {tree_width, depth,
    # mean_accepted_path} rider travel together
    decode_treespec = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_treespec = treespec_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"treespec decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # tensor-parallel paged serving over a tp=4 mesh (ISSUE 7): the
    # mixed-length paged workload sharded across chips, with the
    # aggregate-vs-single-chip scaling factor riding the record (needs
    # >= 4 devices; a single-chip tunnel run records it null)
    decode_tp = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            tp_tps = tp_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
            decode_tp = (tp_tps, {
                "tp": 4,
                "vs_single_chip": (round(tp_tps / decode_paged_tps, 3)
                                   if decode_paged_tps else None)})
        except Exception as e:
            print(f"tp decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # 2-D tp x dp serving mesh (ISSUE 17): the same mixed-length paged
    # workload with the decode batch SPLIT over a dp axis on top of
    # tp=2 — db rows per dp shard, so dp multiplies the rows each step
    # advances; the vs-1-D-tp ratio rides the record (needs >= 4
    # devices; a single-chip tunnel run records it null)
    decode_tp2d = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            tp2d_tps = tp2d_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
            decode_tp2d = (tp2d_tps, {
                "tp": 2, "dp": 2,
                "vs_1d_tp": (round(tp2d_tps / decode_tp[0], 3)
                             if decode_tp and decode_tp[0] else None)})
        except Exception as e:
            print(f"tp2d decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # disaggregated serving cluster (ISSUE 9): two replicas behind the
    # prefix-affinity router on a shared-prefix tenant workload, with
    # the cluster-vs-single-engine ratio riding the record
    decode_cluster = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_cluster = cluster_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"cluster decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # multi-process overhead rider (ISSUE 19): the same disaggregated
    # shape as a process tree behind the socket RPC control plane —
    # rpc wall per step, handoff wire cost and the vs-in-process ratio
    # ride the cluster tier's record
    decode_multiproc = None
    if decode_cluster is not None and (not on_tpu or remaining() > 120):
        try:
            decode_multiproc = multiproc_overhead_tier(on_tpu)
        except Exception as e:
            print(f"multiproc overhead rider failed: "
                  f"{type(e).__name__}: {e}"[:500], file=sys.stderr)

    # hierarchical KV host tier (ISSUE 10): the scheduler tier's bursty
    # preempt workload with swap-out/swap-in instead of evict/replay —
    # swap-in latency + the vs-replay ratio ride the record
    decode_offload = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_offload = offload_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"offload decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # goodput-under-SLO (ISSUE 13): the trace-driven traffic harness
    # against the autoscaling cluster — goodput, deadline-met fraction,
    # p99 TTFT and the autoscale event counts ride the record
    decode_slo = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_slo = slo_goodput_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"slo goodput bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    # multi-tenant adapter plane (ISSUE 14): many LoRA variants through
    # one engine's slot pool vs the single-merged-model deployment —
    # throughput + the adapter-density rider travel together
    decode_multilora = None
    if decode_tps is not None and (not on_tpu or remaining() > 120):
        try:
            decode_multilora = multilora_decode_tier(
                state.params, cfg, db, dp_len, dnew, on_tpu)
        except Exception as e:
            print(f"multilora decode bench failed: {type(e).__name__}: "
                  f"{e}"[:500], file=sys.stderr)

    phases = None
    if not on_tpu or remaining() > 75:
        phases = _capture_phases(step, state, tokens, cfg)

    return _result(tps, mfu, seq, batch, cfg, lossv, decode_tps,
                   decode_int8_tps, decode_int4_tps, decode_w8kv8_tps,
                   decode_paged_tps, decode_prefix_tps,
                   decode_sched=decode_sched, decode_spec=decode_spec,
                   decode_treespec=decode_treespec,
                   decode_tp=decode_tp, decode_tp2d=decode_tp2d,
                   decode_cluster=decode_cluster,
                   decode_multiproc=decode_multiproc,
                   decode_offload=decode_offload, decode_slo=decode_slo,
                   decode_fused=decode_fused,
                   decode_multilora=decode_multilora, phases=phases)


_BATCH_HINT = "/tmp/paddle_tpu_bench_batch_hint"
RC_OOM_RETRY = 17  # child: OOM, deadline hit — parent should respawn at hint


def child_main():
    plat = os.environ.get("PADDLE_TPU_BENCH_PLATFORM")
    if plat:  # local/CI smoke runs; driver runs on the real chip
        import jax
        jax.config.update("jax_platforms", plat)
    # persisted compiles: a watchdog-killed attempt's programs survive
    # into the retry instead of re-burning the tunnel window
    enable_persistent_compilation_cache()
    # The HBM-tier batch scaling in pick_config has only been validated on
    # 16G v5e; if it overshoots on another chip, halve the batch instead of
    # wasting a live tunnel on an OOM crash (VERDICT r2 weak #2). Each
    # compile+OOM cycle costs minutes, so the halving ladder is persisted
    # across child processes (_BATCH_HINT) and the child re-execs (rc=17)
    # rather than risk the parent watchdog killing a mid-ladder attempt.
    budget = int(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "600"))
    t0 = time.perf_counter()
    batch_override = None
    try:
        with open(_BATCH_HINT) as f:
            batch_override = int(f.read().strip())
    except Exception:
        pass
    def emit(r):
        print(json.dumps(r))
        sys.stdout.flush()

    while True:
        try:
            result = measure(batch_override, on_headline=emit, t_start=t0)
            break
        except Exception as e:  # noqa: BLE001 — classify, then re-raise
            if not _is_oom(e):
                raise
            _, _, batch = pick_config()
            cur = batch_override if batch_override is not None else batch
            if cur <= 1:
                raise  # OOM even at batch 1 — nothing left to halve
            batch_override = max(1, cur // 2)
            try:
                with open(_BATCH_HINT, "w") as f:
                    f.write(str(batch_override))
            except Exception:
                pass
            print(f"OOM at batch {cur}; retrying with batch "
                  f"{batch_override}", file=sys.stderr)
            if time.perf_counter() - t0 > 0.4 * budget:
                # not enough watchdog left for another compile+measure:
                # hand the ladder back to the parent
                sys.stderr.flush()
                os._exit(RC_OOM_RETRY)
    print(json.dumps(result))
    sys.stdout.flush()
    os._exit(0)  # skip hanging plugin destructors at interpreter exit


#: the probe child's program — module-level so tests can swap in a
#: deterministically hanging child instead of racing jax's init time
_PROBE_CODE = ("import jax, os, sys; d = jax.devices(); "
               "print('PROBE_OK', d[0].platform, len(d)); "
               "sys.stdout.flush(); os._exit(0)")  # skip plugin destructors


def probe_backend(timeout_s: int) -> Optional[str]:
    """Fast tunnel health check: a throwaway child just initializes the
    backend. Returns None when healthy, else an error string — so a dead
    TPU tunnel costs ~probe-timeout per attempt instead of the full
    measurement watchdog (the observed failure mode: jax.devices() hangs
    indefinitely when the tunnel is down).

    HARDENED (rounds 1–5 mostly recorded stale_last_good because the
    probe itself wedged): the child runs in its OWN session/process
    group and a missed deadline is answered with SIGKILL to the whole
    group. ``subprocess.run(timeout=...)`` only SIGKILLs the direct
    child and then blocks in ``communicate()`` until the pipe closes —
    a tunnel-plugin grandchild holding the stdout fd (or a child stuck
    in uninterruptible backend init) kept the parent hanging PAST its
    own watchdog. killpg bounds the probe at ~timeout_s + 5s, hard."""
    if os.environ.get("PADDLE_TPU_BENCH_PLATFORM"):
        return None  # forced-platform smoke runs skip the probe
    import signal
    proc = subprocess.Popen([sys.executable, "-c", _PROBE_CODE],
                            stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True,
                            start_new_session=True)
    killed = False
    try:
        out, _ = proc.communicate(timeout=timeout_s)
    except subprocess.TimeoutExpired:
        killed = True
        try:  # the whole group: the child AND any plugin grandchildren
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            proc.kill()
        try:
            out, _ = proc.communicate(timeout=5)
        except Exception:
            out = ""
    if "PROBE_OK" in (out or ""):
        # a successful init followed by a hung exit still proves the
        # backend (the watchdog-killed destructor case)
        return None
    if killed:
        return (f"backend probe hung >{timeout_s}s (TPU tunnel down?); "
                f"probe child SIGKILLed with its process group")
    tail = (out or "").strip().splitlines()[-3:]
    return f"backend probe failed: {' | '.join(tail)[-400:]}"


_LASTGOOD = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                         "BENCH_LASTGOOD.json")


def _record_last_good(parsed: dict) -> None:
    """Persist the freshest successful TPU measurement so a later dead-tunnel
    failure JSON can still carry a (marked-stale) number. Stamped with
    capture time so the embed can state its age unambiguously."""
    try:
        dev = str(parsed.get("extra", {}).get("device", "")).lower()
        if "tpu" not in dev:
            return  # CPU smoke runs don't overwrite the TPU record
        rec = dict(parsed)
        # deep-copy the extra dict: the merge below must not leak
        # carried-forward values into the caller's parsed object
        rec["extra"] = dict(parsed.get("extra", {}))
        # carry forward decode TIER VALUES the standalone decode bench
        # merged into the record (tools/tpu_watch.sh stage b): a
        # headline-only run reports them null and must not clobber
        # measured numbers. Only _DECODE_TIERS values carry — metadata
        # (decode_source / decode_recorded_at) follows ONLY when a value
        # actually carried, so a later record with genuinely-measured
        # tiers never inherits a stale "carried" label; decode_source is
        # rebuilt PER TIER ({tier: "live"|"carried"}) so a record mixing
        # same-run and inherited numbers attributes each one correctly
        try:
            with open(_LASTGOOD) as f:
                old = json.load(f)
            ox = old.get("extra", {})
            carried = set()
            for k in _DECODE_TIERS:
                if ox.get(k) is not None and \
                        rec.get("extra", {}).get(k) is None:
                    rec.setdefault("extra", {})[k] = ox[k]
                    carried.add(k)
            if carried:
                if "decode_recorded_at" not in rec.get("extra", {}) and \
                        "decode_recorded_at" in ox:
                    rec["extra"]["decode_recorded_at"] = \
                        ox["decode_recorded_at"]
                for tier, rider in _DECODE_RIDERS:
                    if (tier in carried
                            and rec["extra"].get(rider) is None
                            and ox.get(rider) is not None):
                        rec["extra"][rider] = ox[rider]
                _label_decode_source(
                    rec["extra"], carried,
                    reason=("quick_capture"
                            if rec["extra"].get("quick_capture")
                            else "stale_last_good"))
        except Exception:
            pass
        rec["recorded_unix"] = time.time()
        rec["recorded_at"] = time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                           time.gmtime())
        with open(_LASTGOOD, "w") as f:
            json.dump(rec, f)
    except Exception:
        pass


def _emit_headline_from(stdout_text: str, stderr_text: str = "",
                        note: str = "") -> None:
    """If the child's stdout carries a metric line, echo diagnostics +
    the LAST parseable line and exit 0. Shared by the normal-exit and
    watchdog-salvage paths."""
    for line in reversed((stdout_text or "").strip().splitlines()):
        try:
            parsed = json.loads(line)
        except (json.JSONDecodeError, ValueError):
            continue
        if isinstance(parsed, dict) and "metric" in parsed:
            _record_last_good(parsed)
            if note:
                print(note, file=sys.stderr)
            for dl in (stderr_text or "").strip().splitlines()[-5:]:
                print(f"[child] {dl}", file=sys.stderr)
            print(line)
            sys.stdout.flush()
            os._exit(0)


def parent_main():
    """Run the measurement in a watchdog-guarded child; ALWAYS print exactly
    one JSON line.

    Probe schedule (VERDICT r2 weak #1 — adaptive, fail-fast): two quick
    probes catch a transiently flaky tunnel; if both hang, one long patient
    probe catches a slow-but-alive backend. Worst case all-dead:
    ~60+30+60+30+300 = 8 min of probing, then a maximally diagnostic error
    JSON (per-attempt timings + last-known-good measurement marked stale).
    """
    timeout_s = int(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "600"))
    fast_s = int(os.environ.get("PADDLE_TPU_BENCH_PROBE_TIMEOUT", "60"))
    long_s = int(os.environ.get("PADDLE_TPU_BENCH_LONG_PROBE", "300"))
    try:  # a stale hint from an earlier run/chip must not undersize today's
        os.remove(_BATCH_HINT)
    except OSError:
        pass
    schedule = [(fast_s, 30), (fast_s, 30), (long_s, 0)]
    diag = []
    last_err = "unknown"
    measured = 0
    for i, (probe_s, sleep_s) in enumerate(schedule):
        t0 = time.perf_counter()
        perr = probe_backend(probe_s)
        diag.append({"attempt": i + 1, "probe_timeout_s": probe_s,
                     "probe_elapsed_s": round(time.perf_counter() - t0, 1),
                     "probe_error": perr})
        if perr is not None:
            last_err = f"attempt {i + 1}: {perr}"
            if sleep_s and i + 1 < len(schedule):
                time.sleep(sleep_s)
            continue
        # healthy backend: run the measurement (allow one retry on a
        # non-probe failure — e.g. a mid-measurement tunnel drop). An
        # rc=17 child hit the OOM-halving deadline: respawn immediately
        # (the batch hint file carries the ladder forward) without
        # consuming a measure attempt.
        measured += 1
        t0 = time.perf_counter()
        spawns = 0
        while True:
            spawns += 1
            timed_out = False
            try:
                proc = subprocess.run(
                    [sys.executable, os.path.abspath(__file__), "--child"],
                    stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                    text=True, timeout=timeout_s,
                    cwd=os.path.dirname(os.path.abspath(__file__)))
            except subprocess.TimeoutExpired as te:
                # the child prints the headline line the moment it is
                # measured — salvage it from the killed child's pipe
                proc = None
                timed_out = True
                out = te.stdout or b""
                salvaged = (out.decode(errors="replace")
                            if isinstance(out, bytes) else out)
                err = te.stderr or b""
                salvaged_err = (err.decode(errors="replace")
                                if isinstance(err, bytes) else err)
            if (proc is not None and proc.returncode == RC_OOM_RETRY
                    and spawns < 6):
                diag[-1]["oom_respawns"] = spawns
                continue
            break
        if timed_out:
            # watchdog fired: the headline may still be on the pipe
            _emit_headline_from(
                salvaged, salvaged_err,
                note="watchdog killed decode extras; headline salvaged")
            last_err = f"attempt {i + 1}: watchdog timeout after {timeout_s}s"
            diag[-1]["measure"] = last_err
            if measured >= 2:
                break
            continue
        diag[-1]["measure_elapsed_s"] = round(time.perf_counter() - t0, 1)
        _emit_headline_from(proc.stdout, proc.stderr)
        tail = (proc.stderr or proc.stdout or "").strip().splitlines()[-15:]
        last_err = (f"attempt {i + 1}: rc={proc.returncode}; "
                    + " | ".join(tail)[-1500:])
        diag[-1]["measure"] = last_err
        if measured >= 2:
            break
    # LAST RESORT before surrendering to stale_last_good: one SHORT
    # live capture (PADDLE_TPU_BENCH_QUICK: half batch, 3 reps, no
    # decode extras) under a tight watchdog. A tunnel too flaky for the
    # probes or the full measurement often still holds up for the ~2
    # minutes this needs — a live reduced-rep number beats a stale one
    # every time (rounds 1–5 rode stale_last_good for the whole round).
    quick_s = min(timeout_s,
                  int(os.environ.get("PADDLE_TPU_BENCH_QUICK_TIMEOUT",
                                     "240")))
    try:
        qenv = dict(os.environ, PADDLE_TPU_BENCH_QUICK="1")
        try:
            proc = subprocess.run(
                [sys.executable, os.path.abspath(__file__), "--child"],
                stdout=subprocess.PIPE, stderr=subprocess.PIPE,
                text=True, timeout=quick_s, env=qenv,
                cwd=os.path.dirname(os.path.abspath(__file__)))
            q_out, q_err = proc.stdout, proc.stderr
            diag.append({"quick_capture": f"rc={proc.returncode}"})
        except subprocess.TimeoutExpired as te:
            q_out = te.stdout or b""
            q_out = (q_out.decode(errors="replace")
                     if isinstance(q_out, bytes) else q_out)
            q_err = te.stderr or b""
            q_err = (q_err.decode(errors="replace")
                     if isinstance(q_err, bytes) else q_err)
            diag.append(
                {"quick_capture": f"watchdog timeout after {quick_s}s"})
        # exits 0 if a headline line is present (labeled quick_capture)
        _emit_headline_from(
            q_out, q_err,
            note="quick-capture fallback banked a LIVE reduced-"
                 "rep/batch headline after all full attempts failed")
    except Exception as e:  # noqa: BLE001 — fallback must never mask
        diag.append({"quick_capture": f"{type(e).__name__}: {e}"[:200]})
    print(json.dumps(_failure_record(last_err, diag)))
    sys.stdout.flush()
    os._exit(1)


def _failure_record(last_err: str, diag: list) -> dict:
    """The surrender JSON after every probe/measure/quick attempt
    failed: the error + diagnostics, plus the last-known-good record
    marked stale. Each carried decode tier gets a ``decode_fallback``
    label explaining WHY it rides this round's JSON (ISSUE 8
    satellite): ``probe_killed`` when a probe child had to be SIGKILLed
    (the tunnel never even answered — nothing could run), else
    ``stale_last_good`` (attempts ran and failed; the values are
    inherited). Factored out of parent_main so the labeling is unit-
    testable without spawning children."""
    out = {
        "metric": "llama_train_tokens_per_sec_per_chip",
        "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
        "error": last_err,
        "probe_diagnostics": diag,
    }
    try:
        with open(_LASTGOOD) as f:
            lg = json.load(f)
        lg["stale"] = True
        if lg.get("recorded_unix"):
            age = time.time() - lg["recorded_unix"]
            lg["age_seconds"] = round(age)
            # a capture from the last few hours is this ROUND's own live
            # measurement riding a tunnel window — say so explicitly
            lg["same_round_live_capture"] = age < 6 * 3600
        # key off the LAST probe outcome: an early SIGKILLed probe
        # followed by a healthy one (whose measurement then failed)
        # means attempts DID run — that is stale_last_good, not
        # probe_killed
        last_probe = next((d.get("probe_error")
                           for d in reversed(diag or [])
                           if "probe_error" in d), None)
        probe_killed = "SIGKILL" in str(last_probe or "")
        reason = "probe_killed" if probe_killed else "stale_last_good"
        fallback = {k: reason for k in _DECODE_TIERS
                    if lg.get("extra", {}).get(k) is not None}
        if fallback:
            out["decode_fallback"] = fallback
        out["stale_last_good"] = lg
    except Exception:
        pass
    return out


if __name__ == "__main__":
    if len(sys.argv) > 1 and sys.argv[1] == "--child":
        child_main()
    parent_main()
