"""Extra recipe: ERNIE/BERT-style MLM pretraining, dp×tp hybrid.

Beyond the five BASELINE.md rows — covers the encoder model family (the
reference's flagship NLP lineage). tp shards the attention/ffn matmuls;
dp×fsdp shards batch + optimizer state.
"""
import sys

import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import (  # noqa: E402
    parse_args, build_mesh, run_train_bench, dp_sharded_tokens)


def main():
    args = parse_args()
    from paddle_tpu.models import ernie, train

    if args.preset == "full":
        cfg = ernie.ErnieConfig(dtype=jnp.bfloat16, remat=True)  # base
        batch, seq = 16 * max(1, jax.device_count()), 512
    else:
        cfg = ernie.ErnieConfig.tiny()
        batch, seq = 2 * max(1, jax.device_count()), 64

    mesh = build_mesh(("dp", "fsdp", "tp"), (-1, 1, 2))
    step = train.make_train_step(cfg, mesh, model=ernie)
    state = jax.jit(
        lambda k: train.init_train_state(k, cfg, model=ernie),
        out_shardings=train.state_shardings(mesh, cfg, model=ernie))(
        jax.random.key(0))
    tokens = dp_sharded_tokens(mesh, batch, seq, cfg.vocab_size,
                               axes=("dp",))
    run_train_bench(step, state, tokens, "ernie_mlm_tokens_per_sec",
                    iters=args.iters, preset=args.preset,
                    devices=jax.device_count(), params=cfg.num_params())


if __name__ == "__main__":
    main()
