"""BASELINE target #5: MoE with expert parallelism (ERNIE-MoE-style).

Reference recipe: expert-parallel AllToAll; TPU-native: experts sharded
over the ep mesh axis, GShard top-2 capacity routing with einsum
dispatch/combine (the all-to-all rides ICI).
"""
import sys

import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import (  # noqa: E402
    parse_args, build_mesh, run_train_bench, dp_sharded_tokens)


def main():
    args = parse_args()
    from paddle_tpu.models import llama, moe, train

    n = max(1, jax.device_count())
    ep = min(8, n) if args.preset == "full" else (2 if n % 2 == 0 else 1)
    if args.preset == "full":
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=4096,
            num_layers=12, num_heads=16, num_kv_heads=16,
            max_seq_len=2048, dtype=jnp.bfloat16, remat=True,
            moe=moe.MoEConfig(num_experts=ep * 2, top_k=2))
        batch, seq = max(1, n // ep) * 2, 2048
    else:
        cfg = llama.LlamaConfig.tiny(
            num_layers=2, moe=moe.MoEConfig(num_experts=max(2, ep),
                                            top_k=2))
        batch, seq = max(2, n // ep), 64

    mesh = build_mesh(("dp", "ep", "tp"), (-1, ep, 1))
    step = train.make_train_step(cfg, mesh, data_axes=("dp",),
                                 ep_axis="ep")
    state = jax.jit(lambda k: train.init_train_state(k, cfg),
                    out_shardings=train.state_shardings(mesh, cfg))(
        jax.random.key(0))
    tokens = dp_sharded_tokens(mesh, batch, seq, cfg.vocab_size,
                               axes=("dp",))
    run_train_bench(step, state, tokens, "moe_ep_tokens_per_sec",
                    iters=args.iters, preset=args.preset,
                    devices=jax.device_count(), ep=ep, experts=cfg.moe.num_experts)


if __name__ == "__main__":
    main()
