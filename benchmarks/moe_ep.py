"""BASELINE target #5: MoE with expert parallelism (ERNIE-MoE-style).

Reference recipe: expert-parallel AllToAll; TPU-native: experts sharded
over the ep mesh axis, GShard top-2 capacity routing with einsum
dispatch/combine (the all-to-all rides ICI).
"""
import sys

import numpy as np
import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import parse_args, build_mesh, timeit, emit  # noqa: E402


def main():
    args = parse_args()
    from paddle_tpu.models import llama, moe, train

    n = max(1, jax.device_count())
    ep = min(8, n) if args.preset == "full" else (2 if n % 2 == 0 else 1)
    if args.preset == "full":
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=2048, intermediate_size=4096,
            num_layers=12, num_heads=16, num_kv_heads=16,
            max_seq_len=2048, dtype=jnp.bfloat16, remat=True,
            moe=moe.MoEConfig(num_experts=ep * 2, top_k=2))
        batch, seq = max(1, n // ep) * 2, 2048
    else:
        cfg = llama.LlamaConfig.tiny(
            num_layers=2, moe=moe.MoEConfig(num_experts=max(2, ep),
                                            top_k=2))
        batch, seq = max(2, n // ep), 64

    mesh = build_mesh(("dp", "ep", "tp"), (-1, ep, 1))
    step = train.make_train_step(cfg, mesh, data_axes=("dp",),
                                 ep_axis="ep")
    state = jax.jit(lambda k: train.init_train_state(k, cfg),
                    out_shardings=train.state_shardings(mesh, cfg))(
        jax.random.key(0))
    tokens = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch, seq)), jnp.int32),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec(("dp",))))

    holder = {"state": state}

    def one():
        holder["state"], m = step(holder["state"], tokens)
        return m["loss"]

    dt, loss = timeit(one, iters=args.iters)
    emit("moe_ep_tokens_per_sec", batch * seq / dt, "tokens/s",
         preset=args.preset, devices=n, ep=ep,
         experts=cfg.moe.num_experts, loss=float(loss))


if __name__ == "__main__":
    main()
