"""BASELINE target #4: Llama 3D hybrid (dp x pp x tp) + recompute.

Reference recipe: TP x PP x DP with recompute on v5p-32; TPU-native: the
SPMD pipeline wavefront (shard_map + ppermute) with the hand-written
INTERLEAVED 1F1B (VPP) schedule — the round-5 AOT schedule sweep
(tools/aot_validate.py --config 13b --schedule ..., PERF_NOTES) ranked
it first at 31.0 GB/chip vs 38.5 zero-bubble / 38.6 1F1B / 223 AD-VPP,
with the VPP bubble (P-1)/(M*C+P-1) on top; it still fits at 4x global
batch (64.5 GB).
"""
import sys

import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import (  # noqa: E402
    parse_args, build_mesh, run_train_bench, dp_sharded_tokens)


def main():
    args = parse_args()
    from paddle_tpu.models import llama, train, train_pp

    n = max(1, jax.device_count())
    if args.preset == "full":
        cfg = llama.LlamaConfig.llama2_13b(dtype=jnp.bfloat16, remat=True)
        pp, tp = 4, min(8, max(1, n // 8))
        batch, seq, microbatches = 8, 4096, 8
    else:
        pp = 2 if n % 2 == 0 else 1
        tp = 2 if (n // pp) % 2 == 0 else 1
        cfg = llama.LlamaConfig.tiny(num_layers=4)
        batch, seq, microbatches = 4, 64, 2 * pp

    mesh = build_mesh(("dp", "pp", "tp"), (-1, pp, tp))
    chunks = 2
    step = train_pp.make_train_step_pp(
        cfg, mesh, num_microbatches=microbatches,
        schedule="interleave_1f1b", num_chunks=chunks)
    state = jax.jit(lambda k: train.init_train_state(k, cfg),
                    out_shardings=train_pp.state_shardings_pp(mesh, cfg))(
        jax.random.key(0))
    # interleaved schedules need layers in round-robin STORAGE order
    state = train_pp.to_interleave_storage(state, cfg, mesh, chunks)
    tokens = dp_sharded_tokens(mesh, batch, seq, cfg.vocab_size,
                               axes=("dp",))
    run_train_bench(step, state, tokens, "llama_3d_vpp_tokens_per_sec",
                    iters=args.iters, preset=args.preset,
                    devices=jax.device_count(), pp=pp, tp=tp,
                    microbatches=microbatches, chunks=chunks)


if __name__ == "__main__":
    main()
