"""Shared harness for the BASELINE.md benchmark recipes."""
from __future__ import annotations

import argparse
import json
import time

import numpy as np


def parse_args(extra=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--preset", choices=["tiny", "full"], default="tiny")
    ap.add_argument("--iters", type=int, default=10)
    for name, kw in (extra or {}).items():
        ap.add_argument(name, **kw)
    return ap.parse_args()


def build_mesh(axes, factors):
    """Mesh over all visible devices: ``axes`` names sized by ``factors``
    (a -1 factor absorbs the remaining devices)."""
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    n = len(devs)
    sizes = list(factors)
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = max(1, n // known)
    used = int(np.prod(sizes))
    return Mesh(np.asarray(devs[:used]).reshape(sizes), tuple(axes))


def timeit(step_fn, warmup=2, iters=10):
    import jax
    for _ in range(warmup):
        out = step_fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = step_fn()
    jax.block_until_ready(out)
    return (time.perf_counter() - t0) / iters, out


def emit(metric, value, unit, **extra):
    print(json.dumps({"metric": metric, "value": round(float(value), 2),
                      "unit": unit, "extra": extra}))


def run_train_bench(step, state, tokens, metric, iters=10, **extra):
    """Shared measurement skeleton for the train-step recipes: run
    ``step(state, tokens)`` ``iters`` times after warmup and emit the
    tokens/s metric."""
    holder = {"state": state}

    def one():
        holder["state"], m = step(holder["state"], tokens)
        return m["loss"]

    dt, loss = timeit(one, iters=iters)
    b, s = tokens.shape[0], tokens.shape[1]
    emit(metric, b * s / dt, "tokens/s", loss=float(loss), **extra)


def dp_sharded_tokens(mesh, batch, seq, vocab, axes=("dp",)):
    """Random int32 tokens laid out over the mesh's data axes."""
    import jax
    import jax.numpy as jnp
    arr = jnp.asarray(np.random.default_rng(0).integers(
        0, vocab, (batch, seq)), jnp.int32)
    return jax.device_put(arr, jax.sharding.NamedSharding(
        mesh, jax.sharding.PartitionSpec(axes)))
