"""BASELINE target #1: ResNet50 on CIFAR-10-shaped data via Model.fit.

Reference recipe: hapi Model.fit single device; datasets are offline in
this environment, so the data is synthetic CIFAR-shaped (the measured
path — input pipeline + jitted train step — is identical).
"""
import sys
import time

import numpy as np

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import parse_args, emit  # noqa: E402


def main():
    args = parse_args()
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu.io import Dataset
    from paddle_tpu.optimizer import Momentum
    from paddle_tpu.vision import models

    if args.preset == "full":
        net = models.resnet50(num_classes=10)
        n_samples, batch = 2048, 128
    else:
        net = models.resnet18(num_classes=10)
        n_samples, batch = 128, 32

    class FakeCifar(Dataset):
        thread_safe = True

        def __init__(self, n):
            rs = np.random.RandomState(0)
            self.x = rs.rand(n, 3, 32, 32).astype(np.float32)
            self.y = rs.randint(0, 10, n).astype(np.int64)

        def __getitem__(self, i):
            return self.x[i], self.y[i]

        def __len__(self):
            return len(self.x)

    model = paddle.Model(net)
    model.prepare(optimizer=Momentum(0.1, parameters=net.parameters()),
                  loss=nn.CrossEntropyLoss(),
                  metrics=paddle.metric.Accuracy())
    ds = FakeCifar(n_samples)
    model.fit(ds, batch_size=batch, epochs=1, verbose=0,
              num_workers=2)   # warmup/compile epoch
    epochs = max(1, args.iters)
    t0 = time.perf_counter()
    model.fit(ds, batch_size=batch, epochs=epochs, verbose=0,
              num_workers=2)
    dt = time.perf_counter() - t0
    emit("resnet_fit_images_per_sec", n_samples * epochs / dt,
         "images/s", preset=args.preset, batch=batch, epochs=epochs)


if __name__ == "__main__":
    main()
