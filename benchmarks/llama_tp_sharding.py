"""BASELINE target #3: Llama with tensor parallel + ZeRO sharding.

Reference recipe: mp_degree=8 + sharding stage-2; TPU-native: tp axis for
Megatron layers + fsdp axis sharding params/grads/optimizer states.
"""
import sys

import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import (  # noqa: E402
    parse_args, build_mesh, run_train_bench, dp_sharded_tokens)


def main():
    args = parse_args()
    from paddle_tpu.models import llama, train

    n = max(1, jax.device_count())
    tp = min(8, n) if args.preset == "full" else (2 if n % 2 == 0 else 1)
    if args.preset == "full":
        cfg = llama.LlamaConfig.llama2_7b(dtype=jnp.bfloat16, remat=True)
        batch, seq = max(1, n // tp) * 1, 4096
    else:
        cfg = llama.LlamaConfig.tiny(num_layers=2)
        batch, seq = max(2, n // tp), 128

    mesh = build_mesh(("dp", "fsdp", "tp"), (1, -1, tp))
    step = train.make_train_step(cfg, mesh)
    state = jax.jit(lambda k: train.init_train_state(k, cfg),
                    out_shardings=train.state_shardings(mesh, cfg))(
        jax.random.key(0))
    tokens = dp_sharded_tokens(mesh, batch, seq, cfg.vocab_size,
                               axes=("dp", "fsdp"))
    run_train_bench(step, state, tokens, "llama_tp_sharding_tokens_per_sec",
                    iters=args.iters, preset=args.preset,
                    devices=jax.device_count(), tp=tp, params=cfg.num_params())


if __name__ == "__main__":
    main()
