"""BASELINE target #2: GPT-2 data parallel, bf16 (AMP O2-equivalent).

Reference recipe: fleet DP + AMP; TPU-native: dp×fsdp batch sharding with
the bf16 train step (master fp32 optimizer states), XLA fuses the grad
all-reduce into the backward.
"""
import sys

import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import (  # noqa: E402
    parse_args, build_mesh, run_train_bench, dp_sharded_tokens)


def main():
    args = parse_args()
    from paddle_tpu.models import gpt, train

    if args.preset == "full":
        cfg = gpt.GPTConfig.gpt2_124m(dtype=jnp.bfloat16)
        batch, seq = 8 * max(1, jax.device_count()), 1024
    else:
        cfg = gpt.GPTConfig.tiny()
        batch, seq = 2 * max(1, jax.device_count()), 128

    mesh = build_mesh(("dp", "fsdp", "tp"), (-1, 1, 1))
    step = train.make_train_step(cfg, mesh, model=gpt)
    state = jax.jit(lambda k: train.init_train_state(k, cfg, model=gpt),
                    out_shardings=train.state_shardings(
                        mesh, cfg, model=gpt))(jax.random.key(0))
    tokens = dp_sharded_tokens(mesh, batch, seq, cfg.vocab_size,
                               axes=("dp",))
    run_train_bench(step, state, tokens, "gpt2_dp_tokens_per_sec",
                    iters=args.iters, preset=args.preset,
                    devices=jax.device_count(), params=cfg.num_params())


if __name__ == "__main__":
    main()
