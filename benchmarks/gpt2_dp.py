"""BASELINE target #2: GPT-2 data parallel, bf16 (AMP O2-equivalent).

Reference recipe: fleet DP + AMP; TPU-native: dp×fsdp batch sharding with
the bf16 train step (master fp32 optimizer states), XLA fuses the grad
all-reduce into the backward.
"""
import sys

import numpy as np
import jax
import jax.numpy as jnp

import os
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from benchmarks._common import parse_args, build_mesh, timeit, emit  # noqa: E402


def main():
    args = parse_args()
    from paddle_tpu.models import gpt, train

    if args.preset == "full":
        cfg = gpt.GPTConfig.gpt2_124m(dtype=jnp.bfloat16)
        batch, seq = 8 * max(1, jax.device_count()), 1024
    else:
        cfg = gpt.GPTConfig.tiny()
        batch, seq = 2 * max(1, jax.device_count()), 128

    mesh = build_mesh(("dp", "fsdp", "tp"), (-1, 1, 1))
    step = train.make_train_step(cfg, mesh, model=gpt)
    state = jax.jit(lambda k: train.init_train_state(k, cfg, model=gpt),
                    out_shardings=train.state_shardings(
                        mesh, cfg, model=gpt))(jax.random.key(0))
    tokens = jax.device_put(
        jnp.asarray(np.random.default_rng(0).integers(
            0, cfg.vocab_size, (batch, seq)), jnp.int32),
        jax.sharding.NamedSharding(mesh,
                                   jax.sharding.PartitionSpec(("dp",))))

    holder = {"state": state}

    def one():
        holder["state"], m = step(holder["state"], tokens)
        return m["loss"]

    dt, loss = timeit(one, iters=args.iters)
    emit("gpt2_dp_tokens_per_sec", batch * seq / dt, "tokens/s",
         preset=args.preset, devices=jax.device_count(),
         loss=float(loss), params=cfg.num_params())


if __name__ == "__main__":
    main()
