"""Serve a saved model from a pure-C program through the native ABI
(reference workflow: capi_exp/pd_inference_api.h consumed by C/Go
services).

Saves a model with jit.save, builds libpaddle_tpu_capi.so, compiles an
embedded C client with gcc, runs it as a separate NON-PYTHON process,
and checks its output against the Python predictor.

Run: JAX_PLATFORMS=cpu python examples/c_serving.py
"""
import os
import subprocess
import sys
import tempfile

import numpy as np

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.inference as inference
from paddle_tpu import _native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

C_CLIENT = r"""
#include <stdio.h>
#include <stdint.h>
#include <stdlib.h>
#include <stddef.h>

extern int PD_Init(const char*);
extern void* PD_ConfigCreate(void);
extern void PD_ConfigSetModelDir(void*, const char*);
extern void* PD_PredictorCreate(void*);
extern const char* PD_PredictorGetInputName(void*, size_t);
extern const char* PD_PredictorGetOutputName(void*, size_t);
extern void* PD_PredictorGetInputHandle(void*, const char*);
extern void* PD_PredictorGetOutputHandle(void*, const char*);
extern int PD_PredictorRun(void*);
extern void PD_TensorReshape(void*, int, const int64_t*);
extern int PD_TensorCopyFromCpuFloat(void*, const float*);
extern int PD_TensorGetShape(void*, int64_t*, int);
extern int PD_TensorCopyToCpuFloat(void*, float*);
extern const char* PD_GetLastError(void);

int main(int argc, char** argv) {
  PD_Init(argv[1]);
  void* cfg = PD_ConfigCreate();
  PD_ConfigSetModelDir(cfg, argv[2]);
  void* pred = PD_PredictorCreate(cfg);
  if (!pred) { fprintf(stderr, "%s\n", PD_GetLastError()); return 1; }
  void* in = PD_PredictorGetInputHandle(
      pred, PD_PredictorGetInputName(pred, 0));
  int64_t shape[2] = {2, 8};
  PD_TensorReshape(in, 2, shape);
  float x[16];
  for (int i = 0; i < 16; ++i) x[i] = (float)i / 8.0f - 1.0f;
  PD_TensorCopyFromCpuFloat(in, x);
  if (!PD_PredictorRun(pred)) {
    fprintf(stderr, "%s\n", PD_GetLastError()); return 1;
  }
  void* out = PD_PredictorGetOutputHandle(
      pred, PD_PredictorGetOutputName(pred, 0));
  int64_t os_[8];
  int nd = PD_TensorGetShape(out, os_, 8);
  int64_t n = 1;
  for (int i = 0; i < nd; ++i) n *= os_[i];
  float* buf = (float*)malloc(n * sizeof(float));
  PD_TensorCopyToCpuFloat(out, buf);
  for (int64_t i = 0; i < n; ++i) printf("%.6f\n", (double)buf[i]);
  return 0;
}
"""

# 1) save a model
paddle.seed(0)
net = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
net.eval()
workdir = tempfile.mkdtemp()
model_path = os.path.join(workdir, "model")
paddle.jit.save(net, model_path,
                input_spec=[paddle.jit.api.InputSpec([2, 8])])

# 2) build the C ABI and the client
lib = _native.build_capi()
src = os.path.join(workdir, "client.c")
with open(src, "w") as f:
    f.write(C_CLIENT)
exe = os.path.join(workdir, "client")
libdir = os.path.dirname(lib)
subprocess.run(["gcc", src, "-o", exe, f"-L{libdir}",
                f"-l:{os.path.basename(lib)}", f"-Wl,-rpath,{libdir}"],
               check=True)

# 3) run the C client as its own process
env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
proc = subprocess.run([exe, REPO, model_path], env=env, text=True,
                      capture_output=True, timeout=300)
assert proc.returncode == 0, proc.stderr[-1000:]
got = np.array([float(v) for v in proc.stdout.split()],
               np.float32).reshape(2, 4)

# 4) compare with the python predictor
x = (np.arange(16, dtype=np.float32) / 8.0 - 1.0).reshape(2, 8)
ref = net(paddle.to_tensor(x)).numpy()
np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)
print("C client served the artifact; max|err| vs python:",
      float(np.abs(got - ref).max()))
