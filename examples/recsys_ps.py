"""Parameter-server sparse-embedding training (reference workflow:
fleet PS mode + sparse_embedding + QueueDataset), single-process loopback.

Run: JAX_PLATFORMS=cpu PADDLE_RPC_REGISTRY=/tmp/ps_example \
     PADDLE_JOB_ID=ex python examples/recsys_ps.py
"""
import os
import numpy as np

os.environ.setdefault("PADDLE_RPC_REGISTRY", "/tmp/ps_example")
os.environ.setdefault("PADDLE_JOB_ID", "ex")

import paddle_tpu as paddle
from paddle_tpu.distributed import rpc
from paddle_tpu.distributed.ps import PsServer, PsClient, TableConfig
from paddle_tpu.distributed.ps.the_one_ps import sparse_embedding

rpc.init_rpc("server0", rank=0, world_size=1)
try:
    # SSD tier: table bounded by disk, not RAM (kind="ssd")
    PsServer([TableConfig(name="emb", dim=8, kind="ssd", optimizer="sgd",
                          lr=0.1, cache_rows=256)])
    client = PsClient(["server0"])
    rng = np.random.default_rng(0)
    for step in range(5):
        ids = paddle.to_tensor(rng.integers(0, 10_000, (16,)))
        feats = sparse_embedding(client, "emb", ids)     # pull
        loss = (feats ** 2).mean()
        loss.backward()                                  # push-on-backward
        print(f"step {step}: loss={float(loss.numpy()):.5f} "
              f"rows={client.table_size('emb')}")
finally:
    rpc.shutdown()
