"""Parameter-server sparse-embedding training — the full fleet PS
lifecycle (reference workflow: fleet.init(role) on every rank,
init_server/run_server on PSERVER ranks, init_worker/stop_worker on
trainers, strategy.a_sync + k_steps selecting geo-SGD).

This script plays both roles: run as a worker, it re-execs itself with
TRAINING_ROLE=PSERVER as the server process (the reference launcher sets
the same env), then trains sparse embeddings through the geo communicator
against an SSD-tier table.

Run: JAX_PLATFORMS=cpu PADDLE_RPC_REGISTRY=/tmp/ps_example \
     PADDLE_JOB_ID=ex python examples/recsys_ps.py
"""
import os
import subprocess
import sys

import numpy as np

os.environ.setdefault("PADDLE_RPC_REGISTRY", "/tmp/ps_example")
os.environ.setdefault("PADDLE_JOB_ID", "ex")
os.environ.setdefault("PADDLE_PSERVERS_IP_PORT_LIST", "auto:0")  # 1 server

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed.fleet import PaddleCloudRoleMaker
from paddle_tpu.distributed.ps import TableConfig
from paddle_tpu.distributed.ps.the_one_ps import sparse_embedding

if os.environ.get("TRAINING_ROLE") == "PSERVER":
    fleet.init(PaddleCloudRoleMaker(), is_collective=False)
    assert fleet.is_server()
    fleet.init_server()          # tables arrive via worker create_table
    print("SERVER_UP", flush=True)
    fleet.run_server()           # blocks until a worker stops us
    sys.exit(0)

# ---- worker role ----
env = dict(os.environ)
env["TRAINING_ROLE"] = "PSERVER"
srv = subprocess.Popen([sys.executable, os.path.abspath(__file__)],
                       env=env, stdout=subprocess.PIPE, text=True)
assert srv.stdout.readline().strip() == "SERVER_UP"

try:
    strategy = fleet.DistributedStrategy()
    strategy.a_sync = True
    strategy.a_sync_configs = {"k_steps": 2}     # k>0 -> geo-SGD
    fleet.init(PaddleCloudRoleMaker(), is_collective=False,
               strategy=strategy)
    assert fleet.is_worker()

    # SSD tier: table bounded by disk, not RAM (kind="ssd")
    comm = fleet.init_worker(TableConfig(name="emb", dim=8, kind="ssd",
                                         optimizer="sgd", lr=0.1,
                                         cache_rows=256))
    rng = np.random.default_rng(0)
    for step in range(5):
        ids = paddle.to_tensor(rng.integers(0, 10_000, (16,)))
        feats = sparse_embedding(comm, "emb", ids)   # pull (geo-local)
        loss = (feats ** 2).mean()
        loss.backward()                              # push-on-backward
        comm.step()                                  # geo sync every k
        print(f"step {step}: loss={float(loss.numpy()):.5f} "
              f"rows={comm.table_size('emb')}")

    fleet.save_persistables("/tmp/ps_example/ckpt")  # shard-per-server
    fleet.stop_worker()                              # final sync + stop
    srv.wait(timeout=30)
    print("done: server exited", srv.returncode)
finally:
    if srv.poll() is None:   # a worker failure must not strand the
        srv.kill()           # server in run_server forever
