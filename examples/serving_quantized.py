"""Weight-only int8 LLM serving in ~30 lines (reference workflow:
paddle.inference + weight_only_linear fused kernels).

Run: JAX_PLATFORMS=cpu python examples/serving_quantized.py
"""
import numpy as np
import jax
import jax.numpy as jnp

from paddle_tpu.models import llama, generate as gen

cfg = llama.LlamaConfig.tiny(num_layers=2, hidden_size=64, num_heads=4,
                             num_kv_heads=4, intermediate_size=128,
                             vocab_size=256)
params = llama.init_params(jax.random.key(0), cfg)

# one-call weight-only int8: per-channel scales, dequant fused into the
# decode matmuls — halves weight HBM traffic on the bandwidth-bound
# decode loop
qparams = gen.quantize_weights(params, cfg)

prompt = jnp.asarray(np.random.default_rng(0).integers(
    0, cfg.vocab_size, (2, 8)), jnp.int32)
out = gen.generate(qparams, prompt, cfg, max_new_tokens=16,
                   temperature=0.8, top_k=40, eos_token_id=None)
print("generated:", np.asarray(out)[:, 8:])
