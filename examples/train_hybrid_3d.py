"""3D hybrid-parallel (dp x fsdp x tp) Llama training in one jitted step
(reference workflow: fleet.init + distributed_model + hybrid configs).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/train_hybrid_3d.py
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.models import llama, train

mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
            ("dp", "fsdp", "tp"))
cfg = llama.LlamaConfig.tiny(num_layers=2, hidden_size=64, num_heads=4,
                             num_kv_heads=4, intermediate_size=128,
                             vocab_size=256)
step = train.make_train_step(cfg, mesh)          # ZeRO + TP shardings
state = jax.jit(lambda k: train.init_train_state(k, cfg),
                out_shardings=train.state_shardings(mesh, cfg))(
    jax.random.key(0))
tokens = jax.device_put(
    jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 64)), jnp.int32),
    NamedSharding(mesh, P(("dp", "fsdp"))))
for i in range(3):
    state, metrics = step(state, tokens)
    print(f"step {i}: loss={float(metrics['loss']):.4f}")
