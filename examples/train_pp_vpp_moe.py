"""Pipeline-parallel training with the hand-written VPP (interleaved
1F1B) schedule, plus the pp × MoE composition — the round-5 recipe
winners (PERF_NOTES schedule sweep: 31.0 GB/chip on the 13B recipe vs
223 GB for AD-backed VPP; pp2×ep4×tp2 MoE at 33.4 GB/chip).

Run: JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     python examples/train_pp_vpp_moe.py
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from paddle_tpu.models import llama, moe, train, train_pp

# ---- dense Llama under VPP (dp × pp × tp) ------------------------------
mesh = Mesh(np.asarray(jax.devices()[:8]).reshape(2, 2, 2),
            ("dp", "pp", "tp"))
cfg = llama.LlamaConfig.tiny(num_layers=4, hidden_size=64, num_heads=4,
                             num_kv_heads=4, intermediate_size=128,
                             vocab_size=256)
chunks = 2
step = train_pp.make_train_step_pp(cfg, mesh, num_microbatches=4,
                                   schedule="interleave_1f1b",
                                   num_chunks=chunks)
state = jax.jit(lambda k: train.init_train_state(k, cfg),
                out_shardings=train_pp.state_shardings_pp(mesh, cfg))(
    jax.random.key(0))
# interleaved schedules hold each device's chunks contiguously; the
# helper permutes into round-robin storage order (checkpoints store
# canonical order — from_interleave_storage inverts at save time)
state = train_pp.to_interleave_storage(state, cfg, mesh, chunks)
tokens = jax.device_put(
    jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (8, 64)), jnp.int32),
    NamedSharding(mesh, P("dp")))
for i in range(3):
    state, metrics = step(state, tokens)
    print(f"[vpp ] step {i}: loss={float(metrics['loss']):.4f}")

# ---- MoE under the pipeline (dp × pp × ep × tp) ------------------------
# the load-balance aux loss rides the pipeline carry; experts shard
# over the ep axis (GSPMD lowers the dispatch einsums to all-to-alls)
mesh4 = Mesh(np.asarray(jax.devices()[:8]).reshape(1, 2, 2, 2),
             ("dp", "pp", "ep", "tp"))
cfg_moe = llama.LlamaConfig.tiny(
    num_layers=4, hidden_size=32, num_heads=2, num_kv_heads=2,
    intermediate_size=64, vocab_size=64,
    moe=moe.MoEConfig(num_experts=4, top_k=2, capacity_factor=2.0))
step_m = train_pp.make_train_step_pp(cfg_moe, mesh4, num_microbatches=2,
                                     schedule="1f1b")
st_m = jax.jit(lambda k: train.init_train_state(k, cfg_moe),
               out_shardings=train_pp.state_shardings_pp(mesh4, cfg_moe))(
    jax.random.key(1))
toks_m = jax.device_put(
    jnp.asarray(np.random.default_rng(1).integers(
        0, cfg_moe.vocab_size, (4, 32)), jnp.int32),
    NamedSharding(mesh4, P("dp")))
for i in range(3):
    st_m, metrics = step_m(st_m, toks_m)
    print(f"[moe ] step {i}: loss={float(metrics['loss']):.4f}")
print("pp VPP + pp MoE example OK")
