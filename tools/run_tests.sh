#!/bin/bash
# CI entry points (VERDICT r2 weak #6 — the full suite is ~30 min
# single-threaded and this box has 1 core, so parallel workers only
# oversubscribe; the lever is tiering):
#
#   tools/run_tests.sh            # full suite (~30 min)
#   tools/run_tests.sh --fast     # skip @slow (subprocess/integration
#                                 # heavies: driver artifacts, bench
#                                 # smoke, multihost, elastic, perf
#                                 # guards) — the per-commit tier
#   PADDLE_TPU_TEST_WORKERS=4 tools/run_tests.sh  # xdist, for multi-core
set -e
cd "$(dirname "$0")/.."
ARGS=()
if [ "$1" = "--fast" ]; then
  shift
  ARGS+=(-m "not slow")
fi
if [ -n "$PADDLE_TPU_TEST_WORKERS" ]; then
  ARGS+=(-n "$PADDLE_TPU_TEST_WORKERS" --dist loadfile)
fi
exec python -m pytest tests/ -q "${ARGS[@]}" "$@"
