"""Standalone flash-attention kernel tuner for the bench shape.

Times fwd and fwd+bwd at the headline config (B=4, H=12, S=4096, D=128,
bf16, causal) across block tilings — much cheaper than full-step sweeps
(one kernel pair per config instead of a 20-layer model). Run on a live
chip:  python tools/flash_bench.py [--configs bq,bk,bqb,bkb ...]
"""
import json
import os
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")
from paddle_tpu.ops.pallas import flash_attention as fa  # noqa: E402

if os.environ.get("PADDLE_TPU_FLASH_SMOKE"):
    B, H, S, D = 1, 2, 256, 64          # CPU interpret-mode smoke
else:
    B, H, S, D = 4, 12, 4096, 128

CONFIGS = [
    (512, 1024, None, None),     # current default (round-2 retune)
    (512, 1024, 256, 1024),
    (512, 1024, 512, 512),
    (512, 1024, 1024, 512),
    (512, 1024, 256, 512),
    (512, 1024, 1024, 1024),
    (1024, 1024, None, None),
    (512, 2048, 512, 1024),
    # bwd-focused variants (bwd measured at 31% of peak r3 — the retune
    # target, VERDICT r4 weak #1): smaller q-tiles cut the dkv kernel's
    # re-streamed q traffic, larger k-tiles amortize the dq pass
    (256, 1024, 256, 1024),
    (512, 512, 512, 512),
    (256, 1024, 256, 2048),
    (512, 1024, 128, 1024),
]


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WINNER_PATH = os.path.join(_REPO, "FLASH_WINNER.json")
DEFAULT_CFG = (512, 1024, None, None)


def _record_winner(results):
    """Persist the best fwd+bwd config when it beats the built-in default
    by >2%, so flash_attention()'s default-blocks path adopts it on the
    next process (bench.py picks it up without a manual flip). Clears a
    stale record when the default wins — never leave an unmeasured
    adoption in place."""
    ours = [r for r in results if isinstance(r["cfg"], list)]
    if not ours:
        return
    base = next((r for r in ours if tuple(r["cfg"]) == DEFAULT_CFG), None)
    if base is None:
        # targeted sweep without the default config: no basis for either
        # adoption or clearing — leave any existing record untouched
        return
    best = max(ours, key=lambda r: r["fwd_bwd_tflops"])
    if tuple(best["cfg"]) == DEFAULT_CFG or \
            best["fwd_bwd_tflops"] < base["fwd_bwd_tflops"] * 1.02:
        if os.path.exists(WINNER_PATH):
            os.remove(WINNER_PATH)
            print("FLASH_WINNER cleared (default tiling wins)")
        return
    rec = {
        "cfg": best["cfg"],
        "fwd_bwd_tflops": best["fwd_bwd_tflops"],
        "default_fwd_bwd_tflops": base["fwd_bwd_tflops"],
        "gain": round(best["fwd_bwd_tflops"] / base["fwd_bwd_tflops"] - 1, 4),
        "recorded_unix": time.time(),
        "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }
    tmp = WINNER_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
    os.replace(tmp, WINNER_PATH)
    print("FLASH_WINNER " + json.dumps(rec))


def main():
    if len(sys.argv) > 1:
        cfgs = []
        for a in sys.argv[1:]:
            parts = [None if p in ("None", "-") else int(p)
                     for p in a.split(",")]
            cfgs.append(tuple(parts))
    else:
        cfgs = CONFIGS
    results = []
    rs = np.random.RandomState(0)
    q = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    k = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    v = jnp.asarray(rs.randn(B, S, H, D), jnp.bfloat16)
    # causal model-flops for MFU-share accounting: 2*0.5*S^2*D mac*2 ops,
    # fwd qk+av = 2x, bwd = 2.5x fwd (dq, dkv re-do score matmuls)
    fwd_flops = 2 * 2 * 0.5 * B * H * S * S * D

    for bq, bk, bqb, bkb in cfgs:
        def fwd_fn(q, k, v):
            return fa.flash_attention(q, k, v, causal=True, block_q=bq,
                                      block_k=bk, block_q_bwd=bqb,
                                      block_k_bwd=bkb)

        def loss_fn(q, k, v):
            return fwd_fn(q, k, v).astype(jnp.float32).sum()

        jf = jax.jit(fwd_fn)
        jg = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))

        def fence(x):
            # a host transfer is the only reliable fence through the
            # remote-dispatch tunnel (block_until_ready returns early
            # there — it produced >5000 "TF/s" readings on a 197 TF/s
            # chip); same workaround as bench.py's loss fetch
            return float(jnp.sum(x[0, 0].astype(jnp.float32)))

        try:
            fence(jf(q, k, v))
            t0 = time.perf_counter()
            for _ in range(8):
                out = jf(q, k, v)
            fence(out)
            t_fwd = (time.perf_counter() - t0) / 8
            fence(jg(q, k, v)[0])
            t0 = time.perf_counter()
            for _ in range(8):
                g = jg(q, k, v)
            fence(g[0])
            t_all = (time.perf_counter() - t0) / 8
        except Exception as e:  # noqa: BLE001
            print(f"CFG {bq},{bk},{bqb},{bkb} FAIL "
                  f"{type(e).__name__}: {str(e)[:160]}")
            continue
        rec = {
            "cfg": [bq, bk, bqb, bkb],
            "fwd_ms": round(t_fwd * 1e3, 2),
            "fwd_bwd_ms": round(t_all * 1e3, 2),
            "fwd_tflops": round(fwd_flops / t_fwd / 1e12, 1),
            "fwd_bwd_tflops": round(3.5 * fwd_flops / t_all / 1e12, 1),
        }
        results.append(rec)
        print("FLASH_BENCH " + json.dumps(rec))
        sys.stdout.flush()

    if not os.environ.get("PADDLE_TPU_FLASH_SMOKE"):
        _record_winner(results)
    _bench_canonical(q, k, v, fwd_flops)


def _bench_canonical(q, k, v, fwd_flops):
    """Also time jax.experimental.pallas.ops.tpu.flash_attention — the
    canonical TPU kernel, same two-pass bwd decomposition as ours. If it
    beats our kernel on hardware, its block parameters (BlockSizes) are
    the tuning target to adopt."""
    try:
        from jax.experimental.pallas.ops.tpu import flash_attention as jfa
    except Exception as e:
        print(f"canonical kernel unavailable: {e}")
        return
    # their layout is (B, H, S, D)
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)

    def fwd_fn(q, k, v):
        return jfa.flash_attention(q, k, v, causal=True)

    def loss_fn(q, k, v):
        return fwd_fn(q, k, v).astype(jnp.float32).sum()

    jf = jax.jit(fwd_fn)
    jg = jax.jit(jax.grad(loss_fn, argnums=(0, 1, 2)))

    def fence(x):
        return float(jnp.sum(x[0, 0].astype(jnp.float32)))

    try:
        fence(jf(qt, kt, vt))
        t0 = time.perf_counter()
        for _ in range(8):
            out = jf(qt, kt, vt)
        fence(out)
        t_fwd = (time.perf_counter() - t0) / 8
        fence(jg(qt, kt, vt)[0])
        t0 = time.perf_counter()
        for _ in range(8):
            g = jg(qt, kt, vt)
        fence(g[0])
        t_all = (time.perf_counter() - t0) / 8
    except Exception as e:  # noqa: BLE001
        print(f"canonical kernel FAIL {type(e).__name__}: {str(e)[:160]}")
        return
    print("FLASH_BENCH " + json.dumps({
        "cfg": "jax-pallas-ops-canonical",
        "fwd_ms": round(t_fwd * 1e3, 2),
        "fwd_bwd_ms": round(t_all * 1e3, 2),
        "fwd_tflops": round(fwd_flops / t_fwd / 1e12, 1),
        "fwd_bwd_tflops": round(3.5 * fwd_flops / t_all / 1e12, 1),
    }))
    sys.stdout.flush()


if __name__ == "__main__":
    main()
