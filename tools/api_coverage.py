"""API-surface audit: reference ``paddle.*`` public names vs paddle_tpu.

Walks the reference package's ``__all__`` lists (top level + the public
submodules a switching user reaches for) WITHOUT importing the reference
(regex over its ``__init__.py`` files) and checks each name against the
living paddle_tpu package. Writes API_COVERAGE.md.

Usage: PYTHONPATH=/root/repo python tools/api_coverage.py [--write]
"""
from __future__ import annotations

import argparse
import os
import re
import sys

REF = "/root/reference/python/paddle"

# submodule -> path under the reference tree (None = top level)
NAMESPACES = [
    ("paddle", "__init__.py"),
    ("paddle.nn", "nn/__init__.py"),
    ("paddle.nn.functional", "nn/functional/__init__.py"),
    ("paddle.nn.initializer", "nn/initializer/__init__.py"),
    ("paddle.nn.utils", "nn/utils/__init__.py"),
    ("paddle.optimizer", "optimizer/__init__.py"),
    ("paddle.optimizer.lr", "optimizer/lr.py"),
    ("paddle.io", "io/__init__.py"),
    ("paddle.amp", "amp/__init__.py"),
    ("paddle.autograd", "autograd/__init__.py"),
    ("paddle.jit", "jit/__init__.py"),
    ("paddle.static", "static/__init__.py"),
    ("paddle.distributed", "distributed/__init__.py"),
    ("paddle.distributed.fleet", "distributed/fleet/__init__.py"),
    ("paddle.linalg", "linalg/__init__.py"),
    ("paddle.fft", "fft.py"),
    ("paddle.signal", "signal.py"),
    ("paddle.sparse", "sparse/__init__.py"),
    ("paddle.vision", "vision/__init__.py"),
    ("paddle.vision.transforms", "vision/transforms/__init__.py"),
    ("paddle.vision.models", "vision/models/__init__.py"),
    ("paddle.vision.ops", "vision/ops.py"),
    ("paddle.strings", "strings/__init__.py"),
    ("paddle.text", "text/__init__.py"),
    ("paddle.audio", "audio/__init__.py"),
    ("paddle.metric", "metric/__init__.py"),
    ("paddle.distribution", "distribution/__init__.py"),
    ("paddle.incubate", "incubate/__init__.py"),
    ("paddle.quantization", "quantization/__init__.py"),
    ("paddle.device", "device/__init__.py"),
    ("paddle.profiler", "profiler/__init__.py"),
    ("paddle.utils", "utils/__init__.py"),
    ("paddle.version", "version/__init__.py"),
    ("paddle.onnx", "onnx/__init__.py"),
]

# reference names that are GPU/legacy-runtime specific: no TPU meaning,
# documented out of scope (mirrors tools/op_coverage.py OUT_OF_SCOPE).
# ``pstring`` is deliberately IN scope (VERDICT r5 weak #8): the
# strings module ships it (host-tier StringTensor dtype), so the audit
# must check it like any other name — tests/test_audits.py pins this.
OUT_OF_SCOPE = {
    "paddle": {
        "float8_e4m3fn", "float8_e5m2", "raw",
        "CUDAPinnedPlace", "CustomPlace", "XPUPlace", "IPUPlace",
    },
    "paddle.device": {
        "IPUPlace", "CustomPlace", "is_compiled_with_ipu",
        "MLUPlace", "NPUPlace",
    },
    "paddle.static": {
        # IPU-only compilation pipeline
        "IpuStrategy", "IpuCompiledProgram", "ipu_shard_guard",
        "set_ipu_shard",
    },
    "paddle.incubate": {
        # XPU/GPU-runtime specific incubate experiments
        "xpu",
    },
}


def ref_all(path: str):
    full = os.path.join(REF, path)
    if not os.path.exists(full):
        return []
    src = open(full, encoding="utf-8", errors="replace").read()
    m = re.search(r"__all__\s*=\s*\[(.*?)\]", src, re.S)
    if not m:
        return []
    return sorted(set(re.findall(r"['\"]([^'\"]+)['\"]", m.group(1))))


_SENTINEL = object()


def resolve(mod, name):
    """True when the attribute EXISTS (a legitimately-None value like
    paddle.newaxis counts as present)."""
    try:
        return getattr(mod, name, _SENTINEL) is not _SENTINEL
    except Exception:
        return False


def unconditionally_raises(obj) -> bool:
    """True when a claimed function's body is a bare ``raise`` as its
    first statement (docstring aside) — a name that resolves but refuses
    every call must not silently count toward the coverage claim
    (VERDICT r4 weak #5: presence-by-getattr overstated 100%)."""
    import ast
    import inspect
    import textwrap
    if not callable(obj) or isinstance(obj, type):
        return False
    try:
        fn = inspect.unwrap(obj)
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except Exception:
        return False
    node = tree.body[0]
    if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return False
    body = node.body
    if body and isinstance(body[0], ast.Expr) and             isinstance(body[0].value, ast.Constant):
        body = body[1:]
    return bool(body) and isinstance(body[0], ast.Raise)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--write", action="store_true")
    args = ap.parse_args()

    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    import importlib
    import paddle_tpu

    rows = []
    totals = {"yes": 0, "missing": 0, "oos": 0, "raises": 0}
    per_ns = []
    missing_by_ns = {}
    raises_by_ns = {}
    for ns, path in NAMESPACES:
        names = ref_all(path)
        if not names:
            continue
        tgt_name = ns.replace("paddle", "paddle_tpu", 1)
        try:
            tgt = importlib.import_module(tgt_name)
        except Exception:
            tgt = None
        oos = OUT_OF_SCOPE.get(ns, set())
        got = miss = skip = nraise = 0
        misses = []
        raisers = []
        for n in names:
            if n in oos:
                skip += 1
                totals["oos"] += 1
                continue
            ok = tgt is not None and resolve(tgt, n)
            obj = getattr(tgt, n, None) if ok else None
            if not ok and ns == "paddle":
                # tensor methods exported at top level in the reference
                from paddle_tpu._core.tensor import Tensor
                ok = hasattr(Tensor, n)
                obj = getattr(Tensor, n, None)  # honesty check applies too
            if ok and unconditionally_raises(obj):
                # a refusal is not coverage: count it ONLY in the raises
                # column, never in "present" (the headline ratio's
                # denominator still includes it via yes+missing+raises)
                nraise += 1
                totals["raises"] += 1
                raisers.append(n)
                continue
            if ok:
                got += 1
                totals["yes"] += 1
            else:
                miss += 1
                totals["missing"] += 1
                misses.append(n)
        per_ns.append((ns, got, miss, nraise, skip, len(names)))
        if misses:
            missing_by_ns[ns] = misses
        if raisers:
            raises_by_ns[ns] = raisers

    lines = ["# API coverage vs reference `paddle.*` public names\n"]
    lines.append("Generated by `tools/api_coverage.py` — every name in the "
                 "reference namespaces' `__all__` checked against the "
                 "living `paddle_tpu` package.\n")
    total = totals["yes"] + totals["missing"] + totals["raises"]
    pct = 100.0 * totals["yes"] / max(1, total)
    lines.append(f"**{totals['yes']}/{total} in-scope names resolve "
                 f"({pct:.1f}%); {totals['oos']} out-of-scope "
                 "(GPU/XPU/IPU-runtime specific); "
                 f"{totals['raises']} resolve but unconditionally raise "
                 "(honesty column — a refusal is not coverage).**\n")
    lines.append("| namespace | present | missing | raises | "
                 "out-of-scope | ref total |")
    lines.append("|---|---|---|---|---|---|")
    for ns, got, miss, nraise, skip, tot in per_ns:
        lines.append(f"| {ns} | {got} | {miss} | {nraise} | {skip} | "
                     f"{tot} |")
    lines.append("\n## Missing names by namespace\n")
    for ns, misses in missing_by_ns.items():
        lines.append(f"- **{ns}**: " + ", ".join(f"`{m}`" for m in misses))
    if raises_by_ns:
        lines.append("\n## Present-but-raising names (refusals)\n")
        for ns, raisers in raises_by_ns.items():
            lines.append(f"- **{ns}**: "
                         + ", ".join(f"`{r}`" for r in raisers))
    out = "\n".join(lines) + "\n"
    if args.write:
        open(os.path.join(os.path.dirname(__file__), "..",
                          "API_COVERAGE.md"), "w").write(out)
        print("wrote API_COVERAGE.md")
    print(f"present={totals['yes']} missing={totals['missing']} "
          f"raises={totals['raises']} oos={totals['oos']} pct={pct:.1f}%")
    for ns, misses in missing_by_ns.items():
        print(f"  {ns}: {len(misses)} missing")
    for ns, raisers in raises_by_ns.items():
        print(f"  {ns}: raises -> {', '.join(raisers)}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
