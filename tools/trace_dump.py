#!/usr/bin/env python
"""Render a serving-plane black box for humans (ISSUE 16).

Reads either kind of observability artifact and prints it as text:

- a CRC-framed flight dump (``flight-<ts>.json``, written by
  :meth:`EngineSupervisor.dump_flight` and the crash paths): the
  last-N scheduler-tick table plus a per-request span waterfall of
  the recorded trace tails;
- a Chrome trace-event export (``tracing.export_chrome`` /
  ``profiler`` output): the same waterfall, reconstructed from the
  ``X`` events (pid/tid metadata rows name the replica/slot lanes).

The render functions return plain line lists so the round-trip is
testable without a subprocess (tests/test_tracing.py)::

    python tools/trace_dump.py <path> [--ticks N] [--rid RID]
"""
from __future__ import annotations

import argparse
import json
import os
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))

#: tick-table columns: (header, payload key, width)
_TICK_COLS = (
    ("step", "step", 6), ("commit", "committed", 6),
    ("plan", "planned_tokens", 5), ("rsrv", "reserved_tokens", 5),
    ("budget", "budget", 6), ("dec", "decode_slots", 4),
    ("pre", "prefills", 4), ("queue", "queued", 5),
    ("degr", "degraded", 4), ("fail", "failures", 4),
    ("lsn", "wal_lsn", 6), ("fault", "fault", 18),
)


def _cell(v, width: int) -> str:
    s = "-" if v is None else str(v)
    return s[:width].rjust(width)


def render_ticks(ticks, last: int = 0) -> list:
    """The flight ring as a fixed-width table, newest last."""
    if last:
        ticks = ticks[-last:]
    lines = ["  ".join(h.rjust(w) for h, _k, w in _TICK_COLS)]
    for t in ticks:
        lines.append("  ".join(_cell(t.get(k), w)
                               for _h, k, w in _TICK_COLS))
    return lines


def _lane(span: dict) -> str:
    rep = span.get("replica", -1)
    slot = span.get("slot", -1)
    left = "router" if rep < 0 else f"r{rep}"
    return left if slot < 0 else f"{left}/s{slot}"


def render_trace(tr: dict) -> list:
    """One request trace as a span waterfall: offsets are ms from the
    trace's submit stamp, so cross-replica spans line up on the one
    timeline the stitching promises."""
    t0 = tr.get("submit_ns", 0)
    head = (f"trace {tr.get('trace_id')} rid={tr.get('rid')} "
            f"replicas={tr.get('replicas')} "
            f"spans={tr.get('recorded')} dropped={tr.get('dropped')}"
            + (f" done={tr.get('reason')}" if tr.get("done") else ""))
    lines = [head]
    for s in tr.get("spans", []):
        off = (s.get("start_ns", 0) - t0) / 1e6
        dur = (s.get("end_ns", 0) - s.get("start_ns", 0)) / 1e6
        meta = s.get("meta")
        lines.append(
            f"  +{off:10.3f}ms {dur:9.3f}ms  {_lane(s):>9}  "
            f"{s.get('name')} seq={s.get('seq')}"
            + (f" {meta}" if meta else ""))
    bd = tr.get("ttft_breakdown")
    if bd:
        lines.append("  ttft: " + "  ".join(
            f"{k.removesuffix('_ms')}={v:.3f}ms"
            for k, v in bd.items()))
    return lines


def render_flight(payload: dict, last_ticks: int = 0,
                  rid=None) -> list:
    """A loaded (CRC-verified) flight-dump payload as text."""
    meta = payload.get("meta", {})
    lines = [f"flight dump: reason={payload.get('reason')} "
             f"replica={meta.get('replica')} "
             f"ticks={len(payload.get('ticks', []))}"
             f"/{payload.get('ticks_total')} "
             f"traces={len(payload.get('traces', []))}"]
    extra = payload.get("extra") or {}
    if extra:
        lines.append("extra: " + json.dumps(extra, sort_keys=True))
    lines.append("")
    lines += render_ticks(payload.get("ticks", []), last=last_ticks)
    for tr in payload.get("traces", []):
        if rid is not None and tr.get("rid") != rid:
            continue
        lines.append("")
        lines += render_trace(tr)
    return lines


def render_chrome(doc: dict, rid=None) -> list:
    """A Chrome trace-event export as per-request waterfalls: ``X``
    events regrouped by the ``rid`` arg each span carries, lanes named
    from the pid/tid metadata rows."""
    pids, tids = {}, {}
    by_rid = {}
    for ev in doc.get("traceEvents", []):
        if ev.get("ph") == "M":
            name = (ev.get("args") or {}).get("name")
            if ev.get("name") == "process_name":
                pids[ev.get("pid")] = name
            elif ev.get("name") == "thread_name":
                tids[(ev.get("pid"), ev.get("tid"))] = name
        elif ev.get("ph") == "X":
            r = (ev.get("args") or {}).get("rid")
            by_rid.setdefault(r, []).append(ev)
    lines = []
    for r in sorted(by_rid, key=lambda x: (x is None, x)):
        if rid is not None and r != rid:
            continue
        evs = sorted(by_rid[r], key=lambda e: e.get("ts", 0))
        t0 = evs[0].get("ts", 0)
        if lines:
            lines.append("")
        lines.append(f"rid={r} spans={len(evs)}")
        for ev in evs:
            lane = pids.get(ev.get("pid"), f"pid{ev.get('pid')}")
            tl = tids.get((ev.get("pid"), ev.get("tid")))
            if tl:
                lane = f"{lane}/{tl}"
            lines.append(
                f"  +{(ev.get('ts', 0) - t0) / 1e3:10.3f}ms "
                f"{ev.get('dur', 0) / 1e3:9.3f}ms  {lane:>16}  "
                f"{ev.get('name')}")
    return lines


def render_path(path: str, last_ticks: int = 0, rid=None) -> list:
    """Sniff + render either artifact kind (the CLI body, shared with
    the round-trip test)."""
    with open(path, "rb") as f:
        doc = json.load(f)
    if doc.get("magic") == "PTFR":
        from paddle_tpu.observability import flight
        return render_flight(flight.load(path), last_ticks=last_ticks,
                             rid=rid)
    if "traceEvents" in doc:
        return render_chrome(doc, rid=rid)
    raise ValueError(f"{path}: neither a flight dump (PTFR) nor a "
                     f"Chrome trace export (traceEvents)")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("path", help="flight-<ts>.json or a Chrome trace "
                                 "export")
    ap.add_argument("--ticks", type=int, default=0,
                    help="show only the last N scheduler ticks")
    ap.add_argument("--rid", type=int, default=None,
                    help="show only this request's waterfall")
    args = ap.parse_args()
    for line in render_path(args.path, last_ticks=args.ticks,
                            rid=args.rid):
        print(line)
    return 0


if __name__ == "__main__":
    sys.exit(main())
