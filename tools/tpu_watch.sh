#!/bin/bash
# Poll the TPU tunnel; whenever it's healthy AND the last-good capture is
# older than REFRESH_S, run bench.py and record the result. Keeps
# BENCH_LASTGOOD.json fresh to end-of-round so a dead-tunnel driver run
# still carries a recent timestamped number (VERDICT r3 weak #1/#10);
# the refresh interval keeps the chip mostly idle for the driver's own
# end-of-round bench.
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_watch.log}
REFRESH_S=${REFRESH_S:-10800}   # re-bench at most every 3h
EXTRAS_DONE=0
while true; do
  # skip entirely while the record is fresh
  if python - <<EOF
import json, os, sys, time
try:
    with open("BENCH_LASTGOOD.json") as f:
        lg = json.load(f)
    fresh = time.time() - lg.get("recorded_unix", 0) < $REFRESH_S
except Exception:
    fresh = False
sys.exit(0 if fresh else 1)
EOF
  then
    sleep 240
    continue
  fi
  if timeout 90 python -c "import jax, os, sys; d = jax.devices(); assert d[0].platform == 'tpu'; print('PROBE_OK', d[0].device_kind); sys.stdout.flush(); os._exit(0)" >>"$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel up — running bench" >>"$LOG"
    # outer timeout must exceed bench.py's own worst case (probe schedule
    # ~8 min + up to two 900 s measure attempts)
    PADDLE_TPU_BENCH_TIMEOUT=900 timeout 2700 python bench.py >/tmp/bench_live.json 2>>"$LOG"
    cat /tmp/bench_live.json >>"$LOG"
    # success only if the captured line parses as JSON with value > 0
    if python - <<'EOF'
import json, sys
try:
    with open("/tmp/bench_live.json") as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    sys.exit(0 if lines and json.loads(lines[-1])["value"] > 0 else 1)
except Exception:
    sys.exit(1)
EOF
    then
      if [ "$EXTRAS_DONE" = "0" ]; then
        echo "$(date -u +%FT%TZ) bench captured; running perf sweep" >>"$LOG"
        timeout 3000 python tools/perf_sweep.py >/tmp/perf_sweep.out 2>&1
        echo "$(date -u +%FT%TZ) perf sweep done (rc=$?)" >>"$LOG"
        timeout 1500 python tools/step_profile.py >/tmp/step_profile.out 2>&1
        echo "$(date -u +%FT%TZ) step profile done (rc=$?)" >>"$LOG"
        timeout 1500 python tools/flash_bench.py >/tmp/flash_bench.out 2>&1
        echo "$(date -u +%FT%TZ) flash bench done (rc=$?)" >>"$LOG"
        EXTRAS_DONE=1
      else
        echo "$(date -u +%FT%TZ) bench refreshed (extras already ran)" >>"$LOG"
      fi
      # stay armed: the loop re-benches when the record ages past REFRESH_S
    else
      echo "$(date -u +%FT%TZ) bench failed despite probe ok; retrying later" >>"$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >>"$LOG"
  fi
  sleep 240
done
