#!/bin/bash
# Poll the TPU tunnel; whenever it's healthy, bank evidence in the
# VERDICT r4 priority order:
#   (a) flash_bench retune     -> FLASH_WINNER.json (adopted by the kernel)
#   (b) decode_bench           -> artifacts/decode_live.json + merged into
#                                 BENCH_LASTGOOD extras (the four serving
#                                 tiers have their own budget: the in-bench
#                                 extras share the headline watchdog and
#                                 have died to it on every live run)
#   (c) bench.py               -> BENCH_LASTGOOD.json (headline)
#   (d) perf_sweep + step_profile (once per round)
# One-time stages (a)(b)(d) run on ANY healthy window regardless of how
# fresh the headline record is; only the re-bench (c) is freshness-gated
# (the round-4 script gated everything, so a fresh headline starved the
# never-run stages). All live captures land in artifacts/.
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_watch.log}
REFRESH_S=${REFRESH_S:-10800}   # re-bench at most every 3h
LOCK=/tmp/tpu_watch.pid
if [ -f "$LOCK" ] && kill -0 "$(cat "$LOCK")" 2>/dev/null; then
  echo "watcher already running (pid $(cat "$LOCK"))" >&2
  exit 0
fi
echo $$ >"$LOCK"
trap 'rm -f "$LOCK"' EXIT
mkdir -p artifacts artifacts/xla_cache
# persistent XLA compilation cache shared by every stage below (and by
# bench.py/decode_bench.py's own enable_persistent_compilation_cache):
# a short tunnel window banks all decode tiers instead of burning
# itself recompiling programs a killed earlier window already built
export JAX_COMPILATION_CACHE_DIR="$PWD/artifacts/xla_cache"
FLASH_DONE=0
DECODE_DONE=0
EXTRAS_DONE=0
while true; do
  if timeout 90 python -c "import jax, os, sys; d = jax.devices(); assert d[0].platform == 'tpu'; print('PROBE_OK', d[0].device_kind); sys.stdout.flush(); os._exit(0)" >>"$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel up" >>"$LOG"
    # (a) flash retune first: its FLASH_WINNER feeds the bench that follows
    if [ "$FLASH_DONE" = "0" ]; then
      echo "$(date -u +%FT%TZ) running flash bench (retune)" >>"$LOG"
      timeout 2400 python tools/flash_bench.py >artifacts/flash_bench_live.out 2>&1
      rc=$?
      echo "$(date -u +%FT%TZ) flash bench done (rc=$rc)" >>"$LOG"
      if grep -q FLASH_BENCH artifacts/flash_bench_live.out; then FLASH_DONE=1; fi
    fi
    # (b) serving decode tiers, dedicated budget
    if [ "$DECODE_DONE" = "0" ]; then
      echo "$(date -u +%FT%TZ) running decode bench" >>"$LOG"
      PADDLE_TPU_BENCH_TIMEOUT=2400 timeout 2700 python tools/decode_bench.py >artifacts/decode_live.json 2>>"$LOG"
      rc=$?
      echo "$(date -u +%FT%TZ) decode bench done (rc=$rc)" >>"$LOG"
      # DECODE_DONE tracks the MEASUREMENT only; the record merge below
      # is best-effort and retried on later windows via artifacts/ (a
      # transient merge failure must not re-burn a 45-min decode bench).
      # bench.py's _record_last_good also carries decode keys forward, so
      # a later headline rewrite cannot clobber them.
      if python - <<'EOF'
import json, sys
try:
    with open("artifacts/decode_live.json") as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    sys.exit(0 if json.loads(lines[-1]).get("decode_tokens_per_sec")
             is not None else 1)
except Exception:
    sys.exit(1)
EOF
      then DECODE_DONE=1; fi
    fi
    # merge measured decode tiers into the last-good record (idempotent;
    # runs every window so a once-failed merge self-heals)
    [ -f artifacts/decode_live.json ] && python - <<'EOF' 2>>"$LOG" || true
import json, time
with open("artifacts/decode_live.json") as f:
    lines = [l for l in f.read().splitlines() if l.strip()]
dec = json.loads(lines[-1])
if dec.get("decode_tokens_per_sec") is not None:
    with open("BENCH_LASTGOOD.json") as f:
        lg = json.load(f)
    changed = False
    for k in ("decode_tokens_per_sec", "decode_paged_tokens_per_sec",
              "decode_prefix_tokens_per_sec",
              "decode_sched_tokens_per_sec",
              "decode_spec_tokens_per_sec",
              "decode_treespec_tokens_per_sec",
              "decode_tp_tokens_per_sec",
              "decode_tp2d_tokens_per_sec",
              "decode_cluster_tokens_per_sec",
              "decode_offload_tokens_per_sec",
              "decode_slo_goodput_tokens_per_sec",
              "decode_multilora_tokens_per_sec",
              "decode_int8_tokens_per_sec", "decode_int4_tokens_per_sec",
              "decode_w8kv8_tokens_per_sec"):
        if dec.get(k) is None:
            continue
        if lg.setdefault("extra", {}).get(k) != dec[k]:
            lg["extra"][k] = dec[k]
            changed = True
        # this tier was just MEASURED: shed any stale carried label even
        # when the value repeats exactly (2-decimal rounding collides).
        # A pre-PR2 blanket string label migrates to the dict form
        # first, seeded with "carried" for every tier it covered — an
        # empty-dict migration would relabel still-carried tiers live.
        src = lg["extra"].get("decode_source")
        if src is not None and not isinstance(src, dict):
            src = lg["extra"]["decode_source"] = {
                t: "carried" for t in (
                    "decode_tokens_per_sec", "decode_paged_tokens_per_sec",
                    "decode_prefix_tokens_per_sec",
                    "decode_int8_tokens_per_sec",
                    "decode_int4_tokens_per_sec",
                    "decode_w8kv8_tokens_per_sec")
                if lg["extra"].get(t) is not None}
            changed = True
        if isinstance(src, dict) and src.get(k) != "live":
            src[k] = "live"
            changed = True
    # rider dicts travel with their tier: the scheduler tier's p50/p99
    # step-latency bound (ISSUE 4), the speculative tier's acceptance
    # rate (ISSUE 5 — the number that explains the tput) and the paged
    # tier's fused-kernel speedup (ISSUE 11)
    for rider in ("decode_sched_step_ms", "decode_spec_acceptance",
                  "decode_treespec_stats",
                  "decode_tp_scaling", "decode_tp2d_scaling",
                  "decode_cluster_scaling",
                  "decode_multiproc_overhead",
                  "decode_offload_resume", "decode_slo_metrics",
                  "decode_fused_speedup",
                  "decode_overlap_speedup",
                  "decode_durability_overhead",
                  "decode_trace_overhead",
                  "decode_multilora_density"):
        ms = dec.get(rider)
        if ms is not None and lg.setdefault("extra", {}).get(rider) != ms:
            lg["extra"][rider] = ms
            changed = True
    if changed:
        lg["extra"]["decode_recorded_at"] = time.strftime(
            "%Y-%m-%dT%H:%M:%SZ", time.gmtime())
        with open("BENCH_LASTGOOD.json", "w") as f:
            json.dump(lg, f)
EOF
    # (c) headline bench, freshness-gated
    if ! python - <<EOF
import json, sys, time
try:
    with open("BENCH_LASTGOOD.json") as f:
        lg = json.load(f)
    sys.exit(0 if time.time() - lg.get("recorded_unix", 0) < $REFRESH_S else 1)
except Exception:
    sys.exit(1)
EOF
    then
      echo "$(date -u +%FT%TZ) running bench" >>"$LOG"
      PADDLE_TPU_BENCH_TIMEOUT=900 timeout 2700 python bench.py >/tmp/bench_live.json 2>>"$LOG"
      cat /tmp/bench_live.json >>"$LOG"
      cp /tmp/bench_live.json artifacts/bench_live.json 2>/dev/null
    fi
    # (d) once-per-round extras, after at least one good headline exists
    if [ "$EXTRAS_DONE" = "0" ] && [ -f BENCH_LASTGOOD.json ]; then
      echo "$(date -u +%FT%TZ) running perf sweep" >>"$LOG"
      timeout 3000 python tools/perf_sweep.py >artifacts/perf_sweep_live.out 2>&1
      echo "$(date -u +%FT%TZ) perf sweep done (rc=$?)" >>"$LOG"
      timeout 1500 python tools/step_profile.py >artifacts/step_profile_live.out 2>&1
      echo "$(date -u +%FT%TZ) step profile done (rc=$?)" >>"$LOG"
      EXTRAS_DONE=1
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >>"$LOG"
  fi
  sleep 240
done
