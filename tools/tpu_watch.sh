#!/bin/bash
# Poll the TPU tunnel; whenever it's healthy, bank evidence in the
# VERDICT r4 priority order:
#   (a) flash_bench retune  -> FLASH_WINNER.json (adopted by the kernel)
#   (b) bench.py            -> BENCH_LASTGOOD.json incl. all decode tiers
#   (c) perf_sweep + step_profile (once per round)
# Then keep BENCH_LASTGOOD.json fresh to end-of-round (re-bench every
# REFRESH_S) so a dead-tunnel driver run still carries a recent number.
# All live captures are copied into artifacts/ so they survive /tmp.
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_watch.log}
REFRESH_S=${REFRESH_S:-10800}   # re-bench at most every 3h
mkdir -p artifacts
FLASH_DONE=0
EXTRAS_DONE=0
while true; do
  # skip entirely while the record is fresh
  if python - <<EOF
import json, os, sys, time
try:
    with open("BENCH_LASTGOOD.json") as f:
        lg = json.load(f)
    fresh = time.time() - lg.get("recorded_unix", 0) < $REFRESH_S
except Exception:
    fresh = False
sys.exit(0 if fresh else 1)
EOF
  then
    sleep 240
    continue
  fi
  if timeout 90 python -c "import jax, os, sys; d = jax.devices(); assert d[0].platform == 'tpu'; print('PROBE_OK', d[0].device_kind); sys.stdout.flush(); os._exit(0)" >>"$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel up" >>"$LOG"
    # (a) flash retune first: its FLASH_WINNER feeds the bench that follows
    if [ "$FLASH_DONE" = "0" ]; then
      echo "$(date -u +%FT%TZ) running flash bench (retune)" >>"$LOG"
      timeout 2400 python tools/flash_bench.py >artifacts/flash_bench_live.out 2>&1
      rc=$?
      echo "$(date -u +%FT%TZ) flash bench done (rc=$rc)" >>"$LOG"
      # done only if at least one config produced a number
      if grep -q FLASH_BENCH artifacts/flash_bench_live.out; then FLASH_DONE=1; fi
    fi
    # (b) headline bench + decode tiers
    echo "$(date -u +%FT%TZ) running bench" >>"$LOG"
    # outer timeout must exceed bench.py's own worst case (probe schedule
    # ~8 min + up to two 900 s measure attempts)
    PADDLE_TPU_BENCH_TIMEOUT=900 timeout 2700 python bench.py >/tmp/bench_live.json 2>>"$LOG"
    cat /tmp/bench_live.json >>"$LOG"
    cp /tmp/bench_live.json artifacts/bench_live.json 2>/dev/null
    # success only if the captured line parses as JSON with value > 0
    if python - <<'EOF'
import json, sys
try:
    with open("/tmp/bench_live.json") as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    sys.exit(0 if lines and json.loads(lines[-1])["value"] > 0 else 1)
except Exception:
    sys.exit(1)
EOF
    then
      if [ "$EXTRAS_DONE" = "0" ]; then
        echo "$(date -u +%FT%TZ) bench captured; running perf sweep" >>"$LOG"
        timeout 3000 python tools/perf_sweep.py >artifacts/perf_sweep_live.out 2>&1
        echo "$(date -u +%FT%TZ) perf sweep done (rc=$?)" >>"$LOG"
        timeout 1500 python tools/step_profile.py >artifacts/step_profile_live.out 2>&1
        echo "$(date -u +%FT%TZ) step profile done (rc=$?)" >>"$LOG"
        EXTRAS_DONE=1
      else
        echo "$(date -u +%FT%TZ) bench refreshed (extras already ran)" >>"$LOG"
      fi
      # stay armed: the loop re-benches when the record ages past REFRESH_S
    else
      echo "$(date -u +%FT%TZ) bench failed despite probe ok; retrying later" >>"$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >>"$LOG"
  fi
  sleep 240
done
