#!/bin/bash
# Poll the TPU tunnel; the moment it's healthy, run bench.py and record the
# result. Keeps BENCH_LASTGOOD.json fresh so a later dead-tunnel driver run
# still carries a recent (marked-stale) number. Exits after first success.
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/tpu_watch.log}
while true; do
  if timeout 90 python -c "import jax, os, sys; d = jax.devices(); assert d[0].platform == 'tpu'; print('PROBE_OK', d[0].device_kind); sys.stdout.flush(); os._exit(0)" >>"$LOG" 2>&1; then
    echo "$(date -u +%FT%TZ) tunnel up — running bench" >>"$LOG"
    # outer timeout must exceed bench.py's own worst case (probe schedule
    # ~8 min + up to two 900 s measure attempts)
    PADDLE_TPU_BENCH_TIMEOUT=900 timeout 2700 python bench.py >/tmp/bench_live.json 2>>"$LOG"
    cat /tmp/bench_live.json >>"$LOG"
    # success only if the captured line parses as JSON with value > 0
    if python - <<'EOF'
import json, sys
try:
    with open("/tmp/bench_live.json") as f:
        lines = [l for l in f.read().splitlines() if l.strip()]
    sys.exit(0 if lines and json.loads(lines[-1])["value"] > 0 else 1)
except Exception:
    sys.exit(1)
EOF
    then
      echo "$(date -u +%FT%TZ) bench captured; running perf sweep" >>"$LOG"
      timeout 3000 python tools/perf_sweep.py >/tmp/perf_sweep.out 2>&1
      echo "$(date -u +%FT%TZ) perf sweep done (rc=$?)" >>"$LOG"
      timeout 1500 python tools/step_profile.py >/tmp/step_profile.out 2>&1
      echo "$(date -u +%FT%TZ) step profile done (rc=$?)" >>"$LOG"
      exit 0
    else
      echo "$(date -u +%FT%TZ) bench failed despite probe ok; retrying later" >>"$LOG"
    fi
  else
    echo "$(date -u +%FT%TZ) tunnel down" >>"$LOG"
  fi
  sleep 240
done
