"""DataLoader tier benchmark: thread pool vs multiprocess workers.

The thread tier caps Python-transform throughput at ~1 core (GIL); the
process tier (io/mp_loader.py) parallelizes it. This measures a
transform-heavy dataset (pure-Python per-sample work, the worst case
for threads) end to end through the public DataLoader API.

Run: python tools/loader_bench.py [num_workers]
Prints one JSON line per tier plus the speedup.
"""
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from paddle_tpu.io import DataLoader, Dataset  # noqa: E402


class TransformHeavyDS(Dataset):
    """Per-sample pure-Python transform (~1 ms of bytecode): stands in
    for tokenization / albumentations-style augmentation pipelines."""

    thread_safe = True

    def __init__(self, n=256, work=4000):
        self.n = n
        self.work = work

    def __getitem__(self, i):
        acc = 0.0
        for k in range(self.work):            # GIL-bound python loop
            acc += (i * 31 + k) % 97
        base = np.full((64, 64), np.float32(acc % 1000))
        return (base + np.float32(i)).astype(np.float32)

    def __len__(self):
        return self.n


def run_tier(num_workers, use_mp):
    os.environ.pop("PADDLE_TPU_LOADER_THREADS", None)
    if not use_mp:
        os.environ["PADDLE_TPU_LOADER_THREADS"] = "1"
    ds = TransformHeavyDS()
    dl = DataLoader(ds, batch_size=16, shuffle=False,
                    num_workers=num_workers, persistent_workers=True)
    # warm epoch (spawn + import cost excluded from the steady-state rate)
    t_cold0 = time.perf_counter()
    n = sum(1 for _ in dl)
    cold = time.perf_counter() - t_cold0
    t0 = time.perf_counter()
    n = sum(1 for _ in dl)
    dt = time.perf_counter() - t0
    os.environ.pop("PADDLE_TPU_LOADER_THREADS", None)
    return {"tier": "process" if use_mp else "thread",
            "num_workers": num_workers, "batches": n,
            "samples_per_sec": round(len(ds) / dt, 1),
            "epoch_s": round(dt, 3), "first_epoch_s": round(cold, 3)}


def main():
    nw = int(sys.argv[1]) if len(sys.argv) > 1 else max(
        2, min(8, (os.cpu_count() or 4) - 1))
    thread = run_tier(nw, use_mp=False)
    print("LOADER_BENCH " + json.dumps(thread))
    proc = run_tier(nw, use_mp=True)
    print("LOADER_BENCH " + json.dumps(proc))
    speedup = proc["samples_per_sec"] / max(thread["samples_per_sec"], 1e-9)
    print("LOADER_BENCH " + json.dumps(
        {"speedup_process_over_thread": round(speedup, 2),
         "cores": os.cpu_count()}))


if __name__ == "__main__":
    main()
