"""PS wire throughput micro-bench (VERDICT r3 weak #8).

Measures pull/push rows/s against a REAL server process over the RPC
wire, across table sizes and batch sizes, for the sync path and the
async/geo communicator tiers — the numbers PERF_NOTES.md records
against the reference's brpc tier
(paddle/fluid/distributed/ps/service/brpc_ps_client.h).

  python tools/ps_bench.py [--dim 64] [--rows 100000] [--batch 2048]

Also prints the per-call wire overhead via a no-payload RPC, and
oneshot-vs-persistent connection comparison (PADDLE_TPU_RPC_ONESHOT=1
forces the old dial-per-call behavior for the A/B).
"""
from __future__ import annotations

import argparse
import json
import multiprocessing as mp
import os
import sys
import tempfile
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def _server_main(registry, dim, ready, stop):
    os.environ["PADDLE_RPC_REGISTRY"] = registry
    os.environ["JAX_PLATFORMS"] = "cpu"
    from paddle_tpu.distributed.rpc import rpc
    from paddle_tpu.distributed.ps import PsServer, TableConfig
    rpc.init_rpc("server0", rank=0, world_size=1)
    PsServer([TableConfig(name="t", dim=dim, optimizer="sgd", lr=0.1)])
    ready.set()
    stop.wait()
    rpc.shutdown()


def _rate(fn, iters, rows_per_iter):
    fn()                      # warm
    t0 = time.perf_counter()
    for _ in range(iters):
        fn()
    dt = time.perf_counter() - t0
    return rows_per_iter * iters / dt, dt / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--dim", type=int, default=64)
    ap.add_argument("--rows", type=int, default=100_000)
    ap.add_argument("--batch", type=int, default=2048)
    ap.add_argument("--iters", type=int, default=30)
    args = ap.parse_args()

    registry = tempfile.mkdtemp(prefix="psbench_")
    os.environ["PADDLE_RPC_REGISTRY"] = registry
    os.environ["JAX_PLATFORMS"] = "cpu"

    ctx = mp.get_context("spawn")
    ready, stop = ctx.Event(), ctx.Event()
    srv = ctx.Process(target=_server_main,
                      args=(registry, args.dim, ready, stop), daemon=True)
    srv.start()
    assert ready.wait(60), "server never came up"

    from paddle_tpu.distributed.rpc import rpc
    from paddle_tpu.distributed.ps import (AsyncCommunicator,
                                           GeoCommunicator, PsClient,
                                           TableConfig)
    rpc.init_rpc("worker0", rank=1, world_size=2)
    rpc.wait_for_workers(["server0"])
    client = PsClient(["server0"])

    rs = np.random.RandomState(0)
    keys = rs.randint(0, args.rows, args.batch).astype(np.int64)
    grads = rs.randn(args.batch, args.dim).astype(np.float32)
    results = {}

    # wire overhead: no-payload round trip
    import paddle_tpu.distributed.fleet.fleet as _fl
    _, rtt = _rate(lambda: rpc.rpc_sync("server0", _fl._srv_done_count),
                   args.iters, 1)
    results["rpc_rtt_us"] = round(rtt * 1e6, 1)

    # sync pull / push
    pull_rps, pull_lat = _rate(
        lambda: client.pull_sparse("t", keys), args.iters, args.batch)
    push_rps, push_lat = _rate(
        lambda: client.push_sparse("t", keys, grads), args.iters,
        args.batch)
    results["sync_pull_rows_per_s"] = round(pull_rps)
    results["sync_push_rows_per_s"] = round(push_rps)
    results["sync_pull_ms"] = round(pull_lat * 1e3, 2)
    results["sync_push_ms"] = round(push_lat * 1e3, 2)

    # async communicator: queued pushes, flush barrier per window
    comm = AsyncCommunicator(client)

    def async_window():
        for _ in range(8):
            comm.push_sparse("t", keys, grads)
        comm.flush()
    a_rps, _ = _rate(async_window, max(args.iters // 8, 2),
                     8 * args.batch)
    comm.stop()
    results["async_push_rows_per_s"] = round(a_rps)

    # geo communicator: local train + delta sync every k steps
    geo = GeoCommunicator(client, trainer_num=1, k_steps=8)
    geo.create_table(TableConfig(name="t", dim=args.dim,
                                 optimizer="sgd", lr=0.1))

    def geo_window():
        for _ in range(8):
            geo.push_sparse("t", keys, grads)
        geo.sync()
    g_rps, _ = _rate(geo_window, max(args.iters // 8, 2),
                     8 * args.batch)
    results["geo_push_rows_per_s"] = round(g_rps)

    results.update(dim=args.dim, batch=args.batch, rows=args.rows,
                   payload_mb_per_batch=round(
                       grads.nbytes / 1e6, 2))
    print(json.dumps({"metric": "ps_wire_bench", **results}))

    stop.set()
    srv.join(timeout=10)
    rpc.shutdown()


if __name__ == "__main__":
    main()
