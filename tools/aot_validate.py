"""AOT-validate the BASELINE north-star configs on a virtual mesh.

VERDICT r3 weak #5: the ``--preset full`` 7B/13B recipes had never been
lowered anywhere. This tool AOT-lowers and compiles them —
``jit(step).lower(...).compile()`` + ``memory_analysis()`` — on a virtual
CPU mesh shaped like the target slice, WITHOUT materializing any state
(``jax.eval_shape`` + sharded ``ShapeDtypeStruct`` arguments), and prints
per-chip memory estimates vs the v5p HBM budget.

The numbers are XLA's own buffer-assignment totals for the per-device SPMD
program: argument space (the sharded train state resident in HBM) + temp
space (activations/workspace). CPU-backend layouts differ from TPU in
padding details, but buffer sizes are dominated by logical shapes, so this
is the right first-order go/no-go for "does config #3/#4 fit v5p".

Usage:  python tools/aot_validate.py [--devices 16] [--config 7b|13b|all]
(re-execs itself with the CPU platform + device count forced, like
``__graft_entry__.dryrun_multichip``).

Reference capability bar: the reference validates memory feasibility only
by running on hardware (no AOT tier); XLA's AOT path is the TPU-native
replacement.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

import numpy as np

V5P_HBM_GB = 95.0  # HBM per v5p chip


def _fmt_gb(nbytes: float) -> float:
    return round(nbytes / (1 << 30), 2)


def _analyze(name, step, state_sds, tokens_sds, mesh, extra):
    import time
    t0 = time.monotonic()
    lowered = step.lower(state_sds, tokens_sds)
    compiled = lowered.compile()
    dt = time.monotonic() - t0
    ma = compiled.memory_analysis()
    row = {
        "config": name,
        "mesh": {a: int(s) for a, s in
                 zip(mesh.axis_names, mesh.devices.shape)},
        "compile_s": round(dt, 1),
        **extra,
    }
    if ma is None:
        row["memory_analysis"] = None
        return row
    arg = float(ma.argument_size_in_bytes)
    out = float(ma.output_size_in_bytes)
    tmp = float(ma.temp_size_in_bytes)
    alias = float(ma.alias_size_in_bytes)
    # donated state aliases input<->output, so resident HBM per chip is
    # arguments (sharded state + tokens) + temps; the aliased output does
    # not double-count
    resident = arg + tmp + max(0.0, out - alias)
    row.update({
        "argument_gb": _fmt_gb(arg),
        "output_gb": _fmt_gb(out),
        "aliased_gb": _fmt_gb(alias),
        "temp_gb": _fmt_gb(tmp),
        "resident_gb_per_chip": _fmt_gb(resident),
        "v5p_hbm_gb": V5P_HBM_GB,
        "fits_v5p": bool(resident / (1 << 30) < V5P_HBM_GB),
        "headroom_gb": round(V5P_HBM_GB - resident / (1 << 30), 2),
    })
    return row


def _state_sds(cfg, mesh, shardings, model=None):
    """Sharded ShapeDtypeStructs for the train state — no allocation."""
    import jax
    from paddle_tpu.models import train
    struct = jax.eval_shape(
        lambda k: train.init_train_state(k, cfg, model=model),
        jax.eval_shape(lambda: jax.random.key(0)))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        struct, shardings)


def _tokens_sds(mesh, batch, seq, axes, seq_axes=None):
    """Sharded tokens ShapeDtypeStruct; ``seq_axes`` optionally shards
    the sequence dim (context parallelism)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P(axes, seq_axes) if seq_axes else P(axes)
    return jax.ShapeDtypeStruct(
        (batch, seq), jnp.int32,
        sharding=NamedSharding(mesh, spec))


def validate_7b(n: int, batch_mult: int = 1):
    """BASELINE #3: Llama-2 7B, TP8 + ZeRO over fsdp (reference recipe:
    mp_degree=8 + sharding stage-2), seq 4096."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.models import llama, train

    tp = min(8, n)
    fsdp = max(1, n // tp)
    mesh = Mesh(np.asarray(jax.devices()[:tp * fsdp]).reshape(1, fsdp, tp),
                ("dp", "fsdp", "tp"))
    cfg = llama.LlamaConfig.llama2_7b(dtype=jnp.bfloat16, remat=True)
    batch = max(1, n // tp) * batch_mult
    step = train.make_train_step(cfg, mesh)
    st_sh = train.state_shardings(mesh, cfg)
    return _analyze(
        "llama2_7b_tp8_zero", step,
        _state_sds(cfg, mesh, st_sh),
        _tokens_sds(mesh, batch, 4096, ("dp", "fsdp")), mesh,
        {"params": cfg.num_params(), "batch": batch, "seq": 4096,
         "remat_policy": cfg.remat_policy})


def validate_13b(n: int, batch_mult: int = 1, schedule: str = "zero_bubble",
                 num_chunks: int = 1):
    """BASELINE #4: Llama-2 13B, 3D hybrid (dp × pp × tp) + recompute,
    seq 4096. ``schedule`` selects the pipeline schedule (VERDICT r4 weak
    #3 / next #6: the original 1F1B figure was bounded by per-microbatch
    activation residency; the VPP/zero-bubble schedules show the headroom —
    probe each via ``--config 13b --schedule {1f1b,zero_bubble,interleave}``
    in separate invocations; one XLA CHECK-crash must not kill the rest)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.models import llama, train, train_pp

    pp = 4
    tp = min(8, max(1, n // pp))
    dp = max(1, n // (pp * tp))
    mesh = Mesh(np.asarray(jax.devices()[:dp * pp * tp]).reshape(dp, pp, tp),
                ("dp", "pp", "tp"))
    cfg = llama.LlamaConfig.llama2_13b(dtype=jnp.bfloat16, remat=True)
    microbatches = 8
    # one sequence per microbatch per dp replica at mult 1
    batch = microbatches * dp * batch_mult
    step = train_pp.make_train_step_pp(cfg, mesh, num_microbatches=microbatches,
                                       schedule=schedule,
                                       num_chunks=num_chunks)
    st_sh = train_pp.state_shardings_pp(mesh, cfg)
    tag = schedule + (f"_c{num_chunks}"
                      if schedule.startswith(("interleave", "vpp")) else "")
    return _analyze(
        f"llama2_13b_3d_{tag}", step,
        _state_sds(cfg, mesh, st_sh),
        _tokens_sds(mesh, batch, 4096, ("dp",)), mesh,
        {"params": cfg.num_params(), "batch": batch, "seq": 4096,
         "microbatches": microbatches, "schedule": tag,
         "remat_policy": cfg.remat_policy})


def validate_moe(n: int, batch_mult: int = 1):
    """BASELINE #5: ERNIE-4.5-style MoE with expert parallelism
    (all-to-all over ICI), seq 4096. Representative mid-size: 16
    experts top-2 over the ep axis."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.models import llama, moe, train

    tp = 2 if n % 2 == 0 else 1
    ep = min(8, max(1, n // (tp * 1)))
    dp = max(1, n // (ep * tp))
    mesh = Mesh(np.asarray(jax.devices()[:dp * ep * tp]).reshape(dp, ep,
                                                                 tp),
                ("dp", "ep", "tp"))
    cfg = llama.LlamaConfig(
        hidden_size=2048, intermediate_size=5632, num_layers=24,
        num_heads=16, num_kv_heads=16, vocab_size=32000,
        max_seq_len=4096, dtype=jnp.bfloat16, remat=True,
        moe=moe.MoEConfig(num_experts=16, top_k=2, capacity_factor=1.25))
    batch = max(1, dp) * 2 * batch_mult
    step = train.make_train_step(cfg, mesh, data_axes=("dp",),
                                 ep_axis="ep")
    st_sh = train.state_shardings(mesh, cfg)
    return _analyze(
        "ernie_moe_ep16", step,
        _state_sds(cfg, mesh, st_sh),
        _tokens_sds(mesh, batch, 4096, ("dp",)), mesh,
        {"params": cfg.num_params(), "batch": batch, "seq": 4096,
         "experts": 16, "top_k": 2, "remat_policy": cfg.remat_policy})


def validate_13b_long(n: int, batch_mult: int = 1, seq: int = 32768):
    """Round-5 long-context evidence: Llama-2 13B at 32k sequence under
    CONTEXT PARALLELISM (GQA-aware ring attention over a cp axis +
    Megatron-SP + ZeRO over fsdp) — the long-context capability the
    framework carries beyond the reference (SURVEY §2.3: the reference
    has no CP). Max sequence is extended past the config default; rope
    tables are computed from the run's seq."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.models import llama, train

    cp = min(4, max(1, n))
    tp = 2 if n // cp >= 2 and (n // cp) % 2 == 0 else 1
    fsdp = max(1, n // (cp * tp))
    mesh = Mesh(
        np.asarray(jax.devices()[:fsdp * cp * tp]).reshape(
            1, fsdp, cp, tp),
        ("dp", "fsdp", "cp", "tp"))
    import dataclasses
    cfg = llama.LlamaConfig.llama2_13b(dtype=jnp.bfloat16, remat=True)
    cfg = dataclasses.replace(cfg, max_seq_len=seq)
    batch = fsdp * batch_mult   # tokens shard over (dp, fsdp)
    step = train.make_train_step(cfg, mesh, data_axes=("dp", "fsdp"),
                                 cp_axis="cp")
    st_sh = train.state_shardings(mesh, cfg)
    return _analyze(
        f"llama2_13b_cp4_seq{seq}", step,
        _state_sds(cfg, mesh, st_sh),
        _tokens_sds(mesh, batch, seq, ("dp", "fsdp"), seq_axes="cp"),
        mesh,
        {"params": cfg.num_params(), "batch": batch, "seq": seq,
         "remat_policy": cfg.remat_policy})


def validate_moe_pp(n: int, batch_mult: int = 1):
    """Round-5 composition: the BASELINE #5 MoE under the PIPELINE engine
    (pp × ep × tp, hand-written VPP schedule) — the reference's pp+MoE
    hybrid. Aux load-balance loss rides the pipeline carry
    (train_pp.make_train_step_pp moe_aux)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh
    from paddle_tpu.models import llama, moe, train, train_pp

    pp = 2
    ep = min(4, max(1, n // (pp * 2)))
    tp = 2 if n % 2 == 0 else 1
    dp = max(1, n // (pp * ep * tp))
    mesh = Mesh(
        np.asarray(jax.devices()[:dp * pp * ep * tp]).reshape(
            dp, pp, ep, tp),
        ("dp", "pp", "ep", "tp"))
    cfg = llama.LlamaConfig(
        hidden_size=2048, intermediate_size=5632, num_layers=24,
        num_heads=16, num_kv_heads=16, vocab_size=32000,
        max_seq_len=4096, dtype=jnp.bfloat16, remat=True,
        moe=moe.MoEConfig(num_experts=16, top_k=2, capacity_factor=1.25))
    microbatches = 4
    batch = microbatches * dp * batch_mult
    step = train_pp.make_train_step_pp(
        cfg, mesh, num_microbatches=microbatches,
        schedule="interleave_1f1b", num_chunks=2)
    st_sh = train_pp.state_shardings_pp(mesh, cfg)
    return _analyze(
        "ernie_moe_pp2_ep_vpp", step,
        _state_sds(cfg, mesh, st_sh),
        _tokens_sds(mesh, batch, 4096, ("dp",)), mesh,
        {"params": cfg.num_params(), "batch": batch, "seq": 4096,
         "microbatches": microbatches, "experts": 16, "top_k": 2,
         "schedule": "interleave_1f1b_c2",
         "remat_policy": cfg.remat_policy})


def validate_serving(n: int, batch_mult: int = 1):
    """ISSUE 3 serving-throughput pack lowering gate: AOT-export the
    RAGGED paged decode kernel (fp + per-row-int8 tiers), the full
    ragged decode step (kernel inside the layer scan), and the
    chunked-prefill step to the TPU platform and require the Mosaic
    ``tpu_custom_call`` where a Pallas kernel is involved — the
    interpret-green-but-won't-lower failure mode of rounds 2/3, gated
    in CI for the new serving programs."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas import paged_attention as pa

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}

    # ragged paged attention op, serving-realistic shapes
    P, page, HK, D, B, pp = 32, 64, 4, 128, 8, 8
    q = jnp.asarray(rs.randn(B, 32, D), jnp.bfloat16)
    kp = jnp.asarray(rs.randn(P, page, HK, D), jnp.bfloat16)
    vp = jnp.asarray(rs.randn(P, page, HK, D), jnp.bfloat16)
    bt = jnp.asarray(rs.randint(1, P, (B, pp)), jnp.int32)
    ln = jnp.asarray(rs.randint(1, pp * page, (B,)), jnp.int32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda *a: pa.paged_attention_kernel(*a)),
            platforms=["tpu"])(q, kp, vp, bt, ln)
    lowered["ragged_paged_fp"] = "tpu_custom_call" in exp.mlir_module()
    k8 = jnp.asarray(rs.randint(-127, 128, (P, page, HK, D)), jnp.int8)
    ks = jnp.asarray(rs.rand(P, page, HK), jnp.float32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, kp, vp, bt, ln, ks, vs:
                    pa.paged_attention_kernel(
                        q, kp, vp, bt, ln, ks_pages=ks, vs_pages=vs)),
            platforms=["tpu"])(q, k8, k8, bt, ln, ks, ks)
    lowered["ragged_paged_int8"] = "tpu_custom_call" in exp.mlir_module()

    # full serving step shapes: ragged decode (kernel in the layer
    # scan) + one chunked-prefill step — export success IS the gate for
    # the pure-XLA parts, the custom call for the kernel part
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    pg = 16
    pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg) + 1,
                                page_size=pg)
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda p, t, pl_, bt_, ln_: gen.paged_decode_forward(
                p, t, pl_, bt_, ln_, cfg, use_kernel=True)),
            platforms=["tpu"])(params, toks, pool, tables, lens)
    lowered["ragged_decode_step"] = "tpu_custom_call" in exp.mlir_module()
    # ISSUE 4 budgeted step program: the SLO scheduler's token budget
    # reaches the device as a decode MASK (deferred slots skip the
    # program) — export the MASKED ragged decode step, the exact
    # program ServingScheduler.step executes, so a mask-handling
    # regression that interprets green but won't Mosaic-lower is gated
    msk = jnp.asarray(rs.rand(B) > 0.5)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda p, t, pl_, bt_, ln_, m:
                    gen.paged_decode_forward(
                        p, t, pl_, bt_, ln_, cfg, active=m,
                        use_kernel=True)),
            platforms=["tpu"])(params, toks, pool, tables, lens, msk)
    lowered["budgeted_decode_step"] = "tpu_custom_call" in exp.mlir_module()
    chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)), jnp.int32)
    exp = jax.export.export(
        jax.jit(lambda p, c, pl_, bt_, cl, kl: gen.paged_prefill_chunk(
            p, c, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl, chunk_len=kl)),
        platforms=["tpu"])(params, chunk, pool, tables[0],
                           jnp.int32(60), jnp.int32(32))
    lowered["chunked_prefill_step"] = True  # export completing is the gate
    # ISSUE 5 speculative decoding: the batched VERIFY program — every
    # speculating row's k-draft chunk scored in one forward against its
    # paged KV (greedy argmax at all positions rides inside the
    # engine's jitted spec program) — exported at serving-realistic
    # shapes; export completing is the gate (pure-XLA gather path, same
    # contract as the chunk program it generalizes)
    spec_chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, 5)),
                             jnp.int32)
    exp = jax.export.export(
        jax.jit(lambda p, c, pl_, bt_, ln_, m: gen.paged_verify_forward(
            p, c, pl_, bt_, ln_, cfg, ctx_cap=64, active=m)),
        platforms=["tpu"])(params, spec_chunk, pool, tables,
                           jnp.minimum(lens, 60), msk)
    lowered["spec_verify_step"] = True
    ok = all(lowered.values())
    return {
        "config": "serving_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        # reuse the pass/fail plumbing: absent on success keeps the row
        # green; an explicit False fails the run like an HBM overflow
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_tp(n: int, batch_mult: int = 1):
    """ISSUE 7 tensor-parallel serving lowering gate: export the
    SHARDED decode/verify programs — weights column-partitioned by the
    regex rules, page pools sharded on the kv-head axis, the per-shard
    body lowered through shard_map with its exact all-gathers — on an
    8-device host mesh to the TPU platform, and require the Mosaic
    ``tpu_custom_call`` where the ragged Pallas kernel is involved.
    Covers both tp regimes: tp=2 shards the tiny config's 2 kv heads;
    tp=4 exercises the GQA KV-REPLICATION path (nkv=2 < tp, one
    replicated head per shard). The interpret-green-but-won't-lower
    failure mode of rounds 2/3, gated for the tp programs."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.serving.paged_cache import pool_partition_specs
    from paddle_tpu.distributed.mesh import serving_mesh

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    n = len(jax.devices())  # the --devices count the parent forced
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    B, pg = 8, 16
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
    msk = jnp.asarray(rs.rand(B) > 0.5)

    def build(tp, kv=None):
        mesh = serving_mesh(tp)
        placed, specs = llama.shard_serving_params(params, cfg, mesh)
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv,
                                    tp=tp)
        # the ONE sharding layout the engine itself uses — shared
        # helper, so this gate can never validate a divergent layout
        pspecs = pool_partition_specs(pool, "tp")
        pool = {nm: jax.device_put(a, NamedSharding(mesh, pspecs[nm]))
                for nm, a in pool.items()}
        return mesh, placed, specs, pool, pspecs

    def export_decode(tag, tp, kv=None):
        mesh, placed, specs, pool, pspecs = build(tp, kv=kv)
        fwd = shard_map(
            lambda p, t, pl_, bt_, ln_, m: gen.paged_decode_forward(
                p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True,
                tp_axis="tp"),
            mesh=mesh, in_specs=(specs, P(), pspecs, P(), P(), P()),
            out_specs=(P(), pspecs), check_rep=False)
        with fa.force_compiled_lowering():
            exp = jax.export.export(jax.jit(fwd), platforms=["tpu"])(
                placed, toks, pool, tables, lens, msk)
        lowered[tag] = "tpu_custom_call" in exp.mlir_module()

    # honor the --devices count: levels the mesh can't hold are skipped
    # with an explicit note instead of crashing a --config all sweep on
    # a small host mesh; with NOTHING validatable the row fails loudly
    if n < 2:
        return {"config": "serving_tp_lowering",
                "compile_s": round(time.monotonic() - t0, 1),
                "lowered": {},
                "skipped": {"all": f"--devices {n} < minimum tp=2; "
                                   f"nothing to shard"},
                "fits_v5p": False}
    export_decode("tp2_ragged_decode_fp", 2)
    export_decode("tp2_ragged_decode_int8", 2, kv="int8")
    if n >= 4:
        export_decode("tp4_gqa_replicated_decode", 4)
    else:
        skipped["tp4_gqa_replicated_decode"] = (
            f"--devices {n} < tp=4 (GQA replication level)")

    # sharded speculative-verify program (pure-XLA gather path — export
    # completing is the gate, same contract as the single-chip config)
    mesh, placed, specs, pool, pspecs = build(2)
    spec_chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, 5)),
                             jnp.int32)
    vfwd = shard_map(
        lambda p, c, pl_, bt_, ln_, m: gen.paged_verify_forward(
            p, c, pl_, bt_, ln_, cfg, ctx_cap=64, active=m,
            tp_axis="tp"),
        mesh=mesh, in_specs=(specs, P(), pspecs, P(), P(), P()),
        out_specs=(P(), pspecs), check_rep=False)
    jax.export.export(jax.jit(vfwd), platforms=["tpu"])(
        placed, spec_chunk, pool, tables, jnp.minimum(lens, 60), msk)
    lowered["tp2_spec_verify_step"] = True
    # sharded continuation-prefill chunk (the resume/prefix program)
    cfwd = shard_map(
        lambda p, c, pl_, bt_, cl, kl: gen.paged_prefill_chunk(
            p, c, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl, chunk_len=kl,
            tp_axis="tp"),
        mesh=mesh, in_specs=(specs, P(), pspecs, P(), P(), P()),
        out_specs=(P(), pspecs), check_rep=False)
    chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)),
                        jnp.int32)
    jax.export.export(jax.jit(cfwd), platforms=["tpu"])(
        placed, chunk, pool, tables[0], jnp.int32(60), jnp.int32(32))
    lowered["tp2_chunked_prefill_step"] = True
    ok = all(lowered.values())
    return {
        "config": "serving_tp_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_tp2d(n: int, batch_mult: int = 1):
    """ISSUE 17 2-D serving-mesh lowering gate: export the
    dp-BATCH-SHARDED step programs — decode (fp + int8-KV), chunked
    prefill and spec verify with their batch args split over the dp
    axis of a ``serving_mesh(tp, dp)`` and the per-layer KV rows +
    scatter indices all-gathered across dp before the pool write —
    plus the EXPERT-PARALLEL MoE decode step (expert stacks sharded
    over dp, per-token all-to-all dispatch) to the TPU platform on the
    8-device host mesh, requiring the Mosaic ``tpu_custom_call`` where
    the ragged Pallas kernel is involved. The interpret-green-but-
    won't-lower failure mode, gated for the 2-D programs."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.models.moe import MoEConfig
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.serving.paged_cache import pool_partition_specs
    from paddle_tpu.distributed.mesh import serving_mesh

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    n = len(jax.devices())  # the --devices count the parent forced
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    mcfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256,
                                  moe=MoEConfig(num_experts=4, top_k=2))
    mparams = llama.init_params(jax.random.key(1), mcfg)
    B, pg = 8, 16
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
    msk = jnp.asarray(rs.rand(B) > 0.5)

    def build(tp, dp, c, p, kv=None):
        mesh = serving_mesh(tp, dp)
        placed, specs = llama.shard_serving_params(p, c, mesh)
        pool = gen.init_paged_cache(c, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv,
                                    tp=tp)
        # head-sharded on tp, REPLICATED across dp — the one layout
        # the engine uses (shared helper; specs never mention dp)
        pspecs = pool_partition_specs(pool, "tp")
        pool = {nm: jax.device_put(a, NamedSharding(mesh, pspecs[nm]))
                for nm, a in pool.items()}
        return mesh, placed, specs, pool, pspecs

    def export_decode(tag, tp, dp, c, p, kv=None, kernel=True):
        mesh, placed, specs, pool, pspecs = build(tp, dp, c, p, kv=kv)
        bspec = P("dp")  # batch args split over the dp axis
        fwd = shard_map(
            lambda pr, t, pl_, bt_, ln_, m: gen.paged_decode_forward(
                pr, t, pl_, bt_, ln_, c, active=m, use_kernel=kernel,
                tp_axis="tp", dp_axis="dp"),
            mesh=mesh,
            in_specs=(specs, bspec, pspecs, bspec, bspec, bspec),
            out_specs=(P(), pspecs), check_rep=False)
        with fa.force_compiled_lowering():
            exp = jax.export.export(jax.jit(fwd), platforms=["tpu"])(
                placed, toks, pool, tables, lens, msk)
        lowered[tag] = (not kernel
                        or "tpu_custom_call" in exp.mlir_module())

    # honor the --devices count: the 2-D gate needs at least a 2x2 grid
    if n < 4:
        return {"config": "serving_tp2d_lowering",
                "compile_s": round(time.monotonic() - t0, 1),
                "lowered": {},
                "skipped": {"all": f"--devices {n} < minimum tp2 x dp2; "
                                   f"nothing to shard"},
                "fits_v5p": False}
    export_decode("tp2dp2_ragged_decode_fp", 2, 2, cfg, params)
    export_decode("tp2dp2_ragged_decode_int8", 2, 2, cfg, params,
                  kv="int8")
    # expert-parallel MoE decode (experts sharded over dp, per-token
    # all-to-all dispatch): pure-XLA path — export completing is the
    # gate, same contract as the spec-verify/chunk programs
    export_decode("tp2dp2_moe_ep_decode", 2, 2, mcfg, mparams,
                  kernel=False)
    if n >= 8:
        export_decode("tp2dp4_moe_ep_decode", 2, 4, mcfg, mparams,
                      kernel=False)
    else:
        skipped["tp2dp4_moe_ep_decode"] = (
            f"--devices {n} < tp2 x dp4 (single-expert-per-shard level)")

    # dp-sharded speculative-verify program (one gather site at the
    # program end: rows axis 1, dst axis 0, logits axis 0)
    mesh, placed, specs, pool, pspecs = build(2, 2, cfg, params)
    spec_chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, 5)),
                             jnp.int32)
    bspec = P("dp")
    vfwd = shard_map(
        lambda p, ch, pl_, bt_, ln_, m: gen.paged_verify_forward(
            p, ch, pl_, bt_, ln_, cfg, ctx_cap=64, active=m,
            tp_axis="tp", dp_axis="dp"),
        mesh=mesh,
        in_specs=(specs, bspec, pspecs, bspec, bspec, bspec),
        out_specs=(P(), pspecs), check_rep=False)
    jax.export.export(jax.jit(vfwd), platforms=["tpu"])(
        placed, spec_chunk, pool, tables, jnp.minimum(lens, 60), msk)
    lowered["tp2dp2_spec_verify_step"] = True
    # dp-REPLICATED continuation-prefill chunk (batch args keep P();
    # dp_axis threads through for the MoE dispatch path)
    cfwd = shard_map(
        lambda p, ch, pl_, bt_, cl, kl: gen.paged_prefill_chunk(
            p, ch, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl, chunk_len=kl,
            tp_axis="tp", dp_axis="dp"),
        mesh=mesh, in_specs=(specs, P(), pspecs, P(), P(), P()),
        out_specs=(P(), pspecs), check_rep=False)
    chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)),
                        jnp.int32)
    jax.export.export(jax.jit(cfwd), platforms=["tpu"])(
        placed, chunk, pool, tables[0], jnp.int32(60), jnp.int32(32))
    lowered["tp2dp2_chunked_prefill_step"] = True
    ok = all(lowered.values())
    return {
        "config": "serving_tp2d_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_cluster(n: int, batch_mult: int = 1):
    """ISSUE 9 disaggregated-cluster lowering gate: AOT-export the
    KV-import scatter program — ``serving.paged_cache._pool_scatter``,
    the EXACT donated program ``PagedKVCache.import_request`` (the
    prefill→decode handoff) and ``restore_prefix`` (drain/restore) run
    — to the TPU platform, at fp and int8-KV pool layouts and at a
    kv-head-SHARDED tp=2 pool (shared ``pool_partition_specs`` layout,
    so this gate can never validate a divergent sharding). Pure-XLA
    scatter: export completing is the gate; the donated pool must
    update in place (a re-materializing lowering would move the whole
    GB-scale pool per handoff)."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.serving.paged_cache import (_pool_scatter,
                                                pool_partition_specs)

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    B, pg, k = 8, 16, 4          # k pages per handoff payload

    def export_scatter(tag, kv=None, tp=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv,
                                    tp=tp)
        if tp is not None:
            from jax.sharding import NamedSharding
            from paddle_tpu.distributed.mesh import serving_mesh
            mesh = serving_mesh(tp)
            pspecs = pool_partition_specs(pool, "tp")
            pool = {nm: jax.device_put(
                a, NamedSharding(mesh, pspecs[nm]))
                for nm, a in pool.items()}
        vals = {nm: np.zeros((a.shape[0], k) + a.shape[2:],
                             a.dtype) for nm, a in pool.items()}
        dst = jnp.asarray(rs.choice(np.arange(1, 2 * B), k,
                                    replace=False).astype(np.int32))
        jax.export.export(
            jax.jit(_pool_scatter, donate_argnums=(0,)),
            platforms=["tpu"])(pool, vals, dst)
        lowered[tag] = True

    export_scatter("kv_import_scatter_fp")
    export_scatter("kv_import_scatter_int8", kv="int8")
    ndev = len(jax.devices())
    if ndev >= 2:
        export_scatter("kv_import_scatter_tp2_sharded", tp=2)
    else:
        skipped["kv_import_scatter_tp2_sharded"] = (
            f"--devices {ndev} < tp=2; sharded scatter not exportable")
    ok = all(lowered.values())
    return {
        "config": "serving_cluster_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_host(n: int, batch_mult: int = 1):
    """ISSUE 10 hierarchical-KV lowering gate: AOT-export the host
    tier's device programs to the TPU platform — the swap-out GATHER
    (``serving.host_tier._pool_gather``, the one read program every
    swap-out/demote/write-through shares) and the swap-in SCATTER
    (``serving.paged_cache._pool_scatter``, the same donated program
    the PR 9 handoff gate already lowers — re-validated here because
    the swap path is its third consumer) — at fp, int8-KV and a
    kv-head-SHARDED tp=2 pool (shared ``pool_partition_specs`` layout).
    Pure-XLA gather/scatter: export completing is the gate."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.serving.host_tier import _pool_gather
    from paddle_tpu.serving.paged_cache import (_pool_scatter,
                                                pool_partition_specs)

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    B, pg, k = 8, 16, 4          # k pages per swap payload

    def build_pool(kv=None, tp=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv,
                                    tp=tp)
        if tp is not None:
            from jax.sharding import NamedSharding
            from paddle_tpu.distributed.mesh import serving_mesh
            mesh = serving_mesh(tp)
            pspecs = pool_partition_specs(pool, "tp")
            pool = {nm: jax.device_put(
                a, NamedSharding(mesh, pspecs[nm]))
                for nm, a in pool.items()}
        return pool

    def export_pair(tag, kv=None, tp=None):
        pool = build_pool(kv=kv, tp=tp)
        ids = jnp.asarray(rs.choice(np.arange(1, 2 * B), k,
                                    replace=False).astype(np.int32))
        jax.export.export(jax.jit(_pool_gather),
                          platforms=["tpu"])(pool, ids)
        lowered[f"swap_out_gather_{tag}"] = True
        vals = {nm: np.zeros((a.shape[0], k) + a.shape[2:], a.dtype)
                for nm, a in pool.items()}
        jax.export.export(
            jax.jit(_pool_scatter, donate_argnums=(0,)),
            platforms=["tpu"])(pool, vals, ids)
        lowered[f"swap_in_scatter_{tag}"] = True

    export_pair("fp")
    export_pair("int8", kv="int8")
    ndev = len(jax.devices())
    if ndev >= 2:
        export_pair("tp2_sharded", tp=2)
    else:
        skipped["swap_tp2_sharded"] = (
            f"--devices {ndev} < tp=2; sharded pool not exportable")
    ok = all(lowered.values())
    return {
        "config": "serving_host_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_lowbit(n: int, batch_mult: int = 1):
    """ISSUE 11 low-bit + fused-kernel lowering gate: Mosaic-lower the
    fused serving kernels and the low-bit decode tiers to the TPU
    platform — (a) the fused dequant+RoPE+ragged-paged-attention decode
    kernel (fp + per-row-int8 pages) and the flash chunk/verify kernel
    (fp + int8 temp cache) at serving-realistic shapes, requiring the
    Mosaic ``tpu_custom_call``; (b) the FULL fused decode step with
    per-group INT4 weights and the w8/kv8 tier (int8 weights + int8-KV
    pool), plus the fused chunk and verify programs; (c) the same
    programs SHARDED on the tp mesh (tp=2 head-sharded KV with int4
    weights, tp=4 GQA-replicated — devices permitting); (d) the fused
    page gather/scatter (``_pool_move``) at fp, int8-KV and tp=2
    layouts, same-pool (defrag) and cross-pool (direct handoff) forms.
    The interpret-green-but-won't-lower failure mode of rounds 2/3,
    gated for every new fused program."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas import serving_fused as sf
    from paddle_tpu.serving.paged_cache import (_pool_move,
                                                pool_partition_specs)

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    ndev = len(jax.devices())

    # (a) op-level kernels, serving-realistic shapes (D=128)
    P_, page, HK, D, B, pp = 32, 64, 4, 128, 8, 8
    q = jnp.asarray(rs.randn(B, 32, D), jnp.bfloat16)
    kp = jnp.asarray(rs.randn(P_, page, HK, D), jnp.bfloat16)
    bt = jnp.asarray(rs.randint(1, P_, (B, pp)), jnp.int32)
    ln = jnp.asarray(rs.randint(1, pp * page, (B,)), jnp.int32)
    cr = jnp.asarray(rs.randn(B, D // 2), jnp.float32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, c, s, kp, vp, bt, ln:
                    sf.fused_paged_decode_kernel(q, c, s, kp, vp, bt,
                                                 ln)),
            platforms=["tpu"])(q, cr, cr, kp, kp, bt, ln)
    lowered["fused_rope_paged_fp"] = "tpu_custom_call" in exp.mlir_module()
    k8 = jnp.asarray(rs.randint(-127, 128, (P_, page, HK, D)), jnp.int8)
    ks = jnp.asarray(rs.rand(P_, page, HK), jnp.float32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, c, s, kp, vp, bt, ln, ks_, vs_:
                    sf.fused_paged_decode_kernel(
                        q, c, s, kp, vp, bt, ln, ks_pages=ks_,
                        vs_pages=vs_)),
            platforms=["tpu"])(q, cr, cr, k8, k8, bt, ln, ks, ks)
    lowered["fused_rope_paged_int8"] = \
        "tpu_custom_call" in exp.mlir_module()
    T, W = 8, 256
    qc = jnp.asarray(rs.randn(B, T, 32, D), jnp.bfloat16)
    ck = jnp.asarray(rs.randn(B, W, HK, D), jnp.bfloat16)
    kst = jnp.asarray(rs.randint(0, W - T, (B,)), jnp.int32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, ck, cv, kst:
                    sf.flash_chunk_attention_kernel(q, ck, cv, W, kst)),
            platforms=["tpu"])(qc, ck, ck, kst)
    lowered["flash_chunk_fp"] = "tpu_custom_call" in exp.mlir_module()
    c8 = jnp.asarray(rs.randint(-127, 128, (B, W, HK, D)), jnp.int8)
    rows = jnp.asarray(rs.rand(B, W, HK), jnp.float32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, ck, cv, kst, kr, vr:
                    sf.flash_chunk_attention_kernel(
                        q, ck, cv, W, kst, k_rows=kr, v_rows=vr)),
            platforms=["tpu"])(qc, c8, c8, kst, rows, rows)
    lowered["flash_chunk_int8"] = "tpu_custom_call" in exp.mlir_module()

    # (b) full fused low-bit step programs, tiny config
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    p_int4 = gen.quantize_weights(params, cfg, bits=4)
    p_int8 = gen.quantize_weights(params, cfg, bits=8)
    pg = 16
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
    msk = jnp.asarray(rs.rand(B) > 0.5)

    def export_step(tag, pp_, kv=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv)
        with fa.force_compiled_lowering():
            exp = jax.export.export(
                jax.jit(lambda p, t, pl_, bt_, ln_, m:
                        gen.paged_decode_forward(
                            p, t, pl_, bt_, ln_, cfg, active=m,
                            use_kernel=True, fused=True)),
                platforms=["tpu"])(pp_, toks, pool, tables, lens, msk)
        lowered[tag] = "tpu_custom_call" in exp.mlir_module()

    export_step("fused_decode_step_int4", p_int4)
    export_step("fused_decode_step_w8kv8", p_int8, kv="int8")
    # fused chunk + verify programs at int4 weights (the flash kernel
    # must Mosaic-lower inside the layer scan too)
    pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg) + 1,
                                page_size=pg)
    chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)), jnp.int32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda p, c, pl_, bt_, cl, kl:
                    gen.paged_prefill_chunk(
                        p, c, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl,
                        chunk_len=kl, fused=True, use_kernel=True)),
            platforms=["tpu"])(p_int4, chunk, pool, tables[0],
                               jnp.int32(60), jnp.int32(32))
    lowered["fused_chunk_step_int4"] = \
        "tpu_custom_call" in exp.mlir_module()
    spec_chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, 5)),
                             jnp.int32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda p, c, pl_, bt_, ln_, m:
                    gen.paged_verify_forward(
                        p, c, pl_, bt_, ln_, cfg, ctx_cap=64, active=m,
                        use_kernel=True, fused=True)),
            platforms=["tpu"])(p_int4, spec_chunk, pool, tables,
                               jnp.minimum(lens, 60), msk)
    lowered["fused_verify_step_int4"] = \
        "tpu_custom_call" in exp.mlir_module()

    # (c) sharded fused low-bit steps on the tp mesh
    def export_tp(tag, tp, pp_, kv=None):
        from paddle_tpu.distributed.mesh import serving_mesh
        mesh = serving_mesh(tp)
        placed, specs = llama.shard_serving_params(pp_, cfg, mesh)
        spool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                     + 1, page_size=pg, kv_dtype=kv,
                                     tp=tp)
        pspecs = pool_partition_specs(spool, "tp")
        spool = {nm: jax.device_put(a, NamedSharding(mesh, pspecs[nm]))
                 for nm, a in spool.items()}
        fwd = shard_map(
            lambda p, t, pl_, bt_, ln_, m: gen.paged_decode_forward(
                p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True,
                tp_axis="tp", fused=True),
            mesh=mesh, in_specs=(specs, P(), pspecs, P(), P(), P()),
            out_specs=(P(), pspecs), check_rep=False)
        with fa.force_compiled_lowering():
            exp = jax.export.export(jax.jit(fwd), platforms=["tpu"])(
                placed, toks, spool, tables, lens, msk)
        lowered[tag] = "tpu_custom_call" in exp.mlir_module()

    if ndev >= 2:
        export_tp("tp2_fused_decode_int4", 2, p_int4)
        export_tp("tp2_fused_decode_w8kv8", 2, p_int8, kv="int8")
    else:
        skipped["tp2_fused_decode"] = (
            f"--devices {ndev} < tp=2; nothing to shard")
    if ndev >= 4:
        export_tp("tp4_gqa_fused_decode_int4", 4, p_int4)
    else:
        skipped["tp4_gqa_fused_decode_int4"] = (
            f"--devices {ndev} < tp=4 (GQA replication level)")

    # (d) fused page gather/scatter (_pool_move): same-pool compaction
    # and cross-pool direct handoff, fp / int8-KV / tp=2-sharded
    def export_move(tag, kv=None, tp=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv,
                                    tp=tp)
        src_pool = jax.tree.map(lambda a: a, pool)
        if tp is not None:
            from paddle_tpu.distributed.mesh import serving_mesh
            mesh = serving_mesh(tp)
            pspecs = pool_partition_specs(pool, "tp")
            pool = {nm: jax.device_put(
                a, NamedSharding(mesh, pspecs[nm]))
                for nm, a in pool.items()}
            src_pool = {nm: jax.device_put(
                a, NamedSharding(mesh, pspecs[nm]))
                for nm, a in src_pool.items()}
        k = 4
        src = jnp.asarray(rs.choice(np.arange(1, 2 * B), k,
                                    replace=False).astype(np.int32))
        dst = jnp.asarray(rs.choice(np.arange(2 * B, 4 * B), k,
                                    replace=False).astype(np.int32))
        jax.export.export(
            jax.jit(lambda pool, s, d: _pool_move(pool, s, d),
                    donate_argnums=(0,)),
            platforms=["tpu"])(pool, src, dst)
        lowered[f"pool_move_compact_{tag}"] = True
        jax.export.export(
            jax.jit(lambda pool, sp, s, d: _pool_move(pool, s, d,
                                                      src_pool=sp),
                    donate_argnums=(0,)),
            platforms=["tpu"])(pool, src_pool, src, dst)
        lowered[f"pool_move_handoff_{tag}"] = True

    export_move("fp")
    export_move("int8", kv="int8")
    if ndev >= 2:
        export_move("tp2_sharded", tp=2)
    else:
        skipped["pool_move_tp2_sharded"] = (
            f"--devices {ndev} < tp=2; sharded move not exportable")
    ok = all(lowered.values())
    return {
        "config": "serving_lowbit_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_treespec(n: int, batch_mult: int = 1):
    """ISSUE 20 tree-speculation lowering gate: Mosaic-lower the
    programs the model-based draft + tree speculation path leaves on
    device — (a) the TREE-MASKED flash chunk/verify kernel (the
    ancestor-bitmask variant of ``flash_chunk_attention_kernel``) at
    serving-realistic shapes, fp AND int8 temp cache, requiring the
    Mosaic ``tpu_custom_call``; (b) the full fused one-forward tree
    verify program (``paged_verify_forward`` in tree mode) over fp and
    int8-KV pools; (c) the DRAFT-MODEL decode step — the truncated-
    layer params from ``make_draft_params`` through the fused paged
    decode program against the second (draft) pool; (d) the tree
    commit program (``paged_tree_commit`` — gather accepted root-path
    rows, scatter into the main pool). The interpret-green-but-won't-
    lower failure mode, gated for the tree path before a chip ever
    sees it."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.ops.pallas import serving_fused as sf
    from paddle_tpu.serving.speculative import (build_comb_tree,
                                                tree_ancestor_matrix,
                                                tree_depths)

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}

    # one realistic comb-tree topology, shared by every stage: width 2,
    # depth 4 -> T = 9 nodes (root + chain + siblings), inside the
    # kernel's 32-node int32 ancestor-bitmask bound
    w, d = 2, 4
    T = 1 + w * d
    tr = build_comb_tree(
        5, np.arange(10, 10 + d, dtype=np.int32),
        [np.arange(50 + i, 50 + i + w - 1, dtype=np.int32)
         for i in range(d)])
    depths1 = tree_depths(tr.parents).astype(np.int32)
    anc1 = tree_ancestor_matrix(tr.parents)

    # (a) op-level tree-masked flash kernel, serving-realistic shapes
    B, W, HK, D = 8, 256, 4, 128
    qc = jnp.asarray(rs.randn(B, T, 32, D), jnp.bfloat16)
    ck = jnp.asarray(rs.randn(B, W, HK, D), jnp.bfloat16)
    kst = jnp.asarray(rs.randint(0, W - T, (B,)), jnp.int32)
    anc = jnp.asarray(np.broadcast_to(anc1, (B, T, T)))
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, ck, cv, kst, tm:
                    sf.flash_chunk_attention_kernel(q, ck, cv, W, kst,
                                                    tree_mask=tm)),
            platforms=["tpu"])(qc, ck, ck, kst, anc)
    lowered["flash_tree_fp"] = "tpu_custom_call" in exp.mlir_module()
    c8 = jnp.asarray(rs.randint(-127, 128, (B, W, HK, D)), jnp.int8)
    rows = jnp.asarray(rs.rand(B, W, HK), jnp.float32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda q, ck, cv, kst, kr, vr, tm:
                    sf.flash_chunk_attention_kernel(
                        q, ck, cv, W, kst, k_rows=kr, v_rows=vr,
                        tree_mask=tm)),
            platforms=["tpu"])(qc, c8, c8, kst, rows, rows, anc)
    lowered["flash_tree_int8"] = "tpu_custom_call" in exp.mlir_module()

    # (b) full fused tree-verify program, tiny config, fp + int8-KV
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    pg = 16
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 60, (B,)), jnp.int32)
    msk = jnp.asarray(rs.rand(B) > 0.5)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, T)), jnp.int32)
    dep = jnp.asarray(np.broadcast_to(depths1, (B, T)))

    def export_tree_verify(tag, kv=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv)
        with fa.force_compiled_lowering():
            exp = jax.export.export(
                jax.jit(lambda p, t, pl_, bt_, ln_, m, dp_, tm:
                        gen.paged_verify_forward(
                            p, t, pl_, bt_, ln_, cfg, ctx_cap=128,
                            active=m, use_kernel=True, fused=True,
                            tree_depth=dp_, tree_mask=tm)),
                platforms=["tpu"])(params, toks, pool, tables, lens,
                                   msk, dep, anc)
        lowered[tag] = "tpu_custom_call" in exp.mlir_module()

    export_tree_verify("tree_verify_step_fp")
    export_tree_verify("tree_verify_step_int8kv", kv="int8")

    # (c) draft-model decode step: truncated-layer params against the
    # second (draft) paged pool through the fused decode program
    dparams, dcfg = gen.make_draft_params(params, cfg, 1)
    dpool = gen.init_paged_cache(dcfg, num_pages=B * (256 // pg) + 1,
                                 page_size=pg)
    dt = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(lambda p, t, pl_, bt_, ln_, m:
                    gen.paged_decode_forward(
                        p, t, pl_, bt_, ln_, dcfg, active=m,
                        use_kernel=True, fused=True)),
            platforms=["tpu"])(dparams, dt, dpool, tables, lens, msk)
    lowered["draft_decode_step"] = "tpu_custom_call" in exp.mlir_module()

    # (d) the tree commit program (pure gather/scatter — no kernel to
    # find, the gate is that it EXPORTS for the platform)
    pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg) + 1,
                                page_size=pg)
    rows_kv = {nm: jnp.zeros((cfg.num_layers, B, T)
                             + a.shape[3:], a.dtype)
               for nm, a in pool.items()}
    pn = jnp.asarray(rs.randint(0, T, (B, T)), jnp.int32)
    pl = jnp.asarray(rs.randint(0, d + 1, (B,)), jnp.int32)
    jax.export.export(
        jax.jit(lambda pool, r, bt_, ln_, n, l:
                gen.paged_tree_commit(pool, r, bt_, ln_, n, l),
                donate_argnums=(0,)),
        platforms=["tpu"])(pool, rows_kv, tables, lens, pn, pl)
    lowered["tree_commit"] = True

    ok = all(lowered.values())
    return {
        "config": "serving_treespec_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "tree": {"width": w, "depth": d, "nodes": T},
        "lowered": lowered,
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_async(n: int, batch_mult: int = 1):
    """ISSUE 12 overlapped-runtime lowering gate: Mosaic-lower the
    programs the double-buffered scheduler leaves IN FLIGHT — the
    masked ragged decode step at fp, int8-KV and per-group INT4
    weights, the batched spec-verify step, and the chunked-prefill
    program COMPOSED with the dispatch-side first-token argmax (the
    overlap pipeline samples on device at dispatch and fetches the
    scalar at commit, so argmax-over-chunk-logits is a new program
    tail that must lower with the chunk forward) — plus the tp=2
    sharded masked decode (devices permitting). The dispatch/commit
    split never changes a program's body, but an interpret-green
    composition that won't lower would stall the pipeline at its very
    first dispatch, so the same gate every other hot path carries
    applies here."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.serving.paged_cache import pool_partition_specs

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    ndev = len(jax.devices())
    B = 8
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    p_int4 = gen.quantize_weights(params, cfg, bits=4)
    pg = 16
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
    msk = jnp.asarray(rs.rand(B) > 0.5)

    def decode_with_sample(p, t, pl_, bt_, ln_, m):
        # the exact in-flight program decode_dispatch launches: masked
        # ragged forward + greedy argmax, pool donated
        logits, pl_ = gen.paged_decode_forward(
            p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), pl_

    def export_decode(tag, pp_, kv=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv)
        with fa.force_compiled_lowering():
            exp = jax.export.export(
                jax.jit(decode_with_sample, donate_argnums=(2,)),
                platforms=["tpu"])(pp_, toks, pool, tables, lens, msk)
        lowered[tag] = "tpu_custom_call" in exp.mlir_module()

    export_decode("overlap_decode_dispatch_fp", params)
    export_decode("overlap_decode_dispatch_int8", params, kv="int8")
    export_decode("overlap_decode_dispatch_int4", p_int4)

    # spec-verify dispatch program (greedy targets at every position)
    pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg) + 1,
                                page_size=pg)
    spec_chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, 5)),
                             jnp.int32)
    jax.export.export(
        jax.jit(lambda p, c, pl_, bt_, ln_, m: gen.paged_verify_forward(
            p, c, pl_, bt_, ln_, cfg, ctx_cap=64, active=m,
            use_kernel=True), donate_argnums=(2,)),
        platforms=["tpu"])(params, spec_chunk, pool, tables,
                           jnp.minimum(lens, 60), msk)
    # pure-XLA gather path (no Pallas kernel unless fused) — export
    # completing is the gate, as in the serving config's verify export
    lowered["overlap_verify_dispatch"] = True

    # chunk program + dispatch-side first-token argmax: the deferred-
    # sample composition new to the overlapped runtime
    chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)), jnp.int32)

    def chunk_with_sample(p, c, pl_, bt_, cl, kl):
        logits, pl_ = gen.paged_prefill_chunk(
            p, c, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl, chunk_len=kl)
        return jnp.argmax(logits[0]), pl_
    jax.export.export(
        jax.jit(chunk_with_sample, donate_argnums=(2,)),
        platforms=["tpu"])(params, chunk, pool, tables[0],
                           jnp.int32(60), jnp.int32(32))
    lowered["overlap_chunk_dispatch_sample"] = True  # export IS the gate

    if ndev >= 2:
        from paddle_tpu.distributed.mesh import serving_mesh
        mesh = serving_mesh(2)
        placed, specs = llama.shard_serving_params(params, cfg, mesh)
        spool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                     + 1, page_size=pg, tp=2)
        pspecs = pool_partition_specs(spool, "tp")
        spool = {nm: jax.device_put(a, NamedSharding(mesh, pspecs[nm]))
                 for nm, a in spool.items()}

        def tp_body(p, t, pl_, bt_, ln_, m):
            logits, pl_ = gen.paged_decode_forward(
                p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True,
                tp_axis="tp")
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pl_
        fwd = shard_map(tp_body, mesh=mesh,
                        in_specs=(specs, P(), pspecs, P(), P(), P()),
                        out_specs=(P(), pspecs), check_rep=False)
        with fa.force_compiled_lowering():
            exp = jax.export.export(
                jax.jit(fwd, donate_argnums=(2,)), platforms=["tpu"])(
                placed, toks, spool, tables, lens, msk)
        lowered["overlap_decode_dispatch_tp2"] = \
            "tpu_custom_call" in exp.mlir_module()
    else:
        skipped["overlap_decode_dispatch_tp2"] = (
            f"--devices {ndev} < tp=2; nothing to shard")
    ok = all(lowered.values())
    return {
        "config": "serving_async_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_adapters(n: int, batch_mult: int = 1):
    """ISSUE 14 multi-LoRA lowering gate: Mosaic-lower the
    adapter-augmented serving programs — the ragged decode step with
    the per-row gathered ``(x @ A_i) @ B_i · α/r`` term at fp, int8-KV
    and per-group INT4 weights, the single-request chunked-prefill and
    batched spec-verify programs with the same term, the tp=2 sharded
    adapter decode (B factors column-sharded with the base weights;
    devices permitting) — plus the CONSTRAINED sampling step (masked
    argmax + the unconstrained-argmax rider the violation counter
    reads). The adapter term is a batched einsum gather and the mask
    one ``where`` — both should fuse into the existing programs — but
    a composition Mosaic rejects would take down every multi-tenant
    engine at its first admission, so the standing lowering gate
    applies."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.ops.pallas import flash_attention as fa
    from paddle_tpu.serving.adapters import AdapterPool
    from paddle_tpu.serving.paged_cache import pool_partition_specs

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    skipped = {}
    ndev = len(jax.devices())
    B = 8
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    p_int4 = gen.quantize_weights(params, cfg, bits=4)
    pg = 16
    tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)), jnp.int32)
    toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)), jnp.int32)
    lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
    msk = jnp.asarray(rs.rand(B) > 0.5)
    pool_a = AdapterPool(cfg, slots=3, rank=4)
    aslot = jnp.asarray(rs.randint(0, 4, (B,)), jnp.int32)

    def adapter_decode(p, t, pl_, bt_, ln_, m, ad, sl):
        logits, pl_ = gen.paged_decode_forward(
            p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True,
            adapters=ad, adapter_slots=sl)
        return jnp.argmax(logits, axis=-1).astype(jnp.int32), pl_

    def export_decode(tag, pp_, kv=None):
        pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                    + 1, page_size=pg, kv_dtype=kv)
        with fa.force_compiled_lowering():
            exp = jax.export.export(
                jax.jit(adapter_decode, donate_argnums=(2,)),
                platforms=["tpu"])(pp_, toks, pool, tables, lens, msk,
                                   pool_a.arrays, aslot)
        lowered[tag] = "tpu_custom_call" in exp.mlir_module()

    export_decode("adapter_decode_fp", params)
    export_decode("adapter_decode_int8", params, kv="int8")
    export_decode("adapter_decode_int4", p_int4)

    # chunked prefill with the one-request adapter term
    pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg) + 1,
                                page_size=pg)
    chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)),
                        jnp.int32)
    jax.export.export(
        jax.jit(lambda p, c, pl_, bt_, cl, kl, ad, sl:
                gen.paged_prefill_chunk(
                    p, c, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl,
                    chunk_len=kl, adapters=ad, adapter_slot=sl),
                donate_argnums=(2,)),
        platforms=["tpu"])(params, chunk, pool, tables[0],
                           jnp.int32(60), jnp.int32(32),
                           pool_a.arrays, aslot[:1])
    lowered["adapter_chunk"] = True          # export IS the gate

    # batched spec verify with the per-row adapter term
    spec_chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (B, 5)),
                             jnp.int32)
    jax.export.export(
        jax.jit(lambda p, c, pl_, bt_, ln_, m, ad, sl:
                gen.paged_verify_forward(
                    p, c, pl_, bt_, ln_, cfg, ctx_cap=64, active=m,
                    adapters=ad, adapter_slots=sl),
                donate_argnums=(2,)),
        platforms=["tpu"])(params, spec_chunk, pool, tables,
                           jnp.minimum(lens, 60), msk,
                           pool_a.arrays, aslot)
    lowered["adapter_verify"] = True

    # the constrained sampling step: masked argmax + the raw-argmax
    # rider (the engine's constraints=True decode program tail)
    cmask = jnp.asarray(rs.rand(B, cfg.vocab_size) > 0.1)

    def constrained_decode(p, t, pl_, bt_, ln_, m, cm):
        logits, pl_ = gen.paged_decode_forward(
            p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True)
        raw = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        nxt = jnp.argmax(jnp.where(cm, logits, -jnp.inf),
                         axis=-1).astype(jnp.int32)
        return (nxt, raw), pl_
    pool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg) + 1,
                                page_size=pg)
    with fa.force_compiled_lowering():
        exp = jax.export.export(
            jax.jit(constrained_decode, donate_argnums=(2,)),
            platforms=["tpu"])(params, toks, pool, tables, lens, msk,
                               cmask)
    lowered["constrained_decode"] = "tpu_custom_call" in \
        exp.mlir_module()

    if ndev >= 2:
        from paddle_tpu.distributed.mesh import serving_mesh
        mesh = serving_mesh(2)
        placed, specs = llama.shard_serving_params(params, cfg, mesh)
        tp_pool = AdapterPool(cfg, slots=3, rank=4, mesh=mesh)
        spool = gen.init_paged_cache(cfg, num_pages=2 * B * (256 // pg)
                                     + 1, page_size=pg, tp=2)
        pspecs = pool_partition_specs(spool, "tp")
        spool = {nm: jax.device_put(a, NamedSharding(mesh, pspecs[nm]))
                 for nm, a in spool.items()}

        def tp_body(p, t, pl_, bt_, ln_, m, ad, sl):
            logits, pl_ = gen.paged_decode_forward(
                p, t, pl_, bt_, ln_, cfg, active=m, use_kernel=True,
                tp_axis="tp", adapters=ad, adapter_slots=sl)
            return jnp.argmax(logits, axis=-1).astype(jnp.int32), pl_
        fwd = shard_map(tp_body, mesh=mesh,
                        in_specs=(specs, P(), pspecs, P(), P(), P(),
                                  tp_pool.specs, P()),
                        out_specs=(P(), pspecs), check_rep=False)
        with fa.force_compiled_lowering():
            exp = jax.export.export(
                jax.jit(fwd, donate_argnums=(2,)), platforms=["tpu"])(
                placed, toks, spool, tables, lens, msk,
                tp_pool.arrays, aslot)
        lowered["adapter_decode_tp2"] = \
            "tpu_custom_call" in exp.mlir_module()
    else:
        skipped["adapter_decode_tp2"] = (
            f"--devices {ndev} < tp=2; nothing to shard")
    ok = all(lowered.values())
    return {
        "config": "serving_adapters_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({"skipped": skipped} if skipped else {}),
        **({} if ok else {"fits_v5p": False}),
    }


def validate_serving_wal(n: int, batch_mult: int = 1):
    """ISSUE 15 cold-restart lowering gate: AOT-export the RECOVERY-
    CRITICAL program set — what a freshly-booted process must compile
    before a ``recover_from_disk`` replay can serve its first token —
    at the crash-sweep geometry, fp and int8-KV:

    - the continuation-prefill REPLAY chunk (``ctx_len > 0`` — every
      journaled session re-enters decode through it),
    - the masked ragged decode step the replayed sessions then run,
    - the checkpoint-prefix restore scatter
      (``paged_cache._pool_scatter`` — the program that writes a WAL
      checkpoint's trie pages back into the fresh pool).

    ``compile_s`` is the headline: it is the compile half of recovery
    MTTR (the replay half is journal-proportional — PERF_NOTES
    'Durability'). Export completing is the gate (pure-XLA paths)."""
    import time
    import numpy as np
    import jax
    import jax.export
    import jax.numpy as jnp
    from paddle_tpu.models import llama, generate as gen
    from paddle_tpu.serving.paged_cache import _pool_scatter

    t0 = time.monotonic()
    rs = np.random.RandomState(0)
    lowered = {}
    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
    params = llama.init_params(jax.random.key(0), cfg)
    B, pg, k = 8, 16, 4

    def export_tier(tag, kv=None):
        pool = gen.init_paged_cache(
            cfg, num_pages=2 * B * (256 // pg) + 1, page_size=pg,
            kv_dtype=kv)
        tables = jnp.asarray(rs.randint(1, B * 4, (B, 256 // pg)),
                             jnp.int32)
        # recovery replay: prompt + tokens[:-1] continues against the
        # session's own pages — a CONTINUATION chunk (ctx_len > 0),
        # not the fresh-prefill shape the serving config lowers
        chunk = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, 32)),
                            jnp.int32)
        jax.export.export(
            jax.jit(lambda p, c, pl_, bt_, cl, kl:
                    gen.paged_prefill_chunk(
                        p, c, pl_, bt_, cfg, ctx_cap=64, ctx_len=cl,
                        chunk_len=kl)),
            platforms=["tpu"])(params, chunk, pool, tables[0],
                               jnp.int32(48), jnp.int32(32))
        lowered[f"recovery_replay_chunk_{tag}"] = True
        toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (B,)),
                           jnp.int32)
        lens = jnp.asarray(rs.randint(1, 200, (B,)), jnp.int32)
        msk = jnp.asarray(rs.rand(B) > 0.5)
        jax.export.export(
            jax.jit(lambda p, t, pl_, bt_, ln_, m:
                    gen.paged_decode_forward(
                        p, t, pl_, bt_, ln_, cfg, active=m)),
            platforms=["tpu"])(params, toks, pool, tables, lens, msk)
        lowered[f"recovered_decode_step_{tag}"] = True
        vals = {nm: np.zeros((a.shape[0], k) + a.shape[2:], a.dtype)
                for nm, a in pool.items()}
        ids = jnp.asarray(rs.choice(np.arange(1, 2 * B), k,
                                    replace=False).astype(np.int32))
        jax.export.export(
            jax.jit(_pool_scatter, donate_argnums=(0,)),
            platforms=["tpu"])(pool, vals, ids)
        lowered[f"ckpt_prefix_restore_{tag}"] = True

    export_tier("fp")
    export_tier("int8", kv="int8")
    ok = all(lowered.values())
    return {
        "config": "serving_wal_lowering",
        "compile_s": round(time.monotonic() - t0, 1),
        "lowered": lowered,
        **({} if ok else {"fits_v5p": False}),
    }


def _impl(args) -> int:
    rows = []

    def emit(row):
        """Print each row the moment it exists: a CHECK-crash in a later
        (bigger) config must not discard the results already produced."""
        print(json.dumps(row))
        sys.stdout.flush()
        rows.append(row)
    if args.config in ("7b", "all"):
        emit(validate_7b(args.devices, args.batch_mult))
    if args.config in ("13b", "all"):
        emit(validate_13b(args.devices, args.batch_mult,
                                 schedule=args.schedule,
                                 num_chunks=args.num_chunks))
    if args.config in ("moe", "all"):
        emit(validate_moe(args.devices, args.batch_mult))
    if args.config in ("moe-pp", "all"):
        emit(validate_moe_pp(args.devices, args.batch_mult))
    if args.config in ("13b-long", "all"):
        emit(validate_13b_long(args.devices, args.batch_mult))
    if args.config in ("serving", "all"):
        emit(validate_serving(args.devices, args.batch_mult))
    if args.config in ("serving-tp", "all"):
        emit(validate_serving_tp(args.devices, args.batch_mult))
    if args.config in ("serving-tp2d", "all"):
        emit(validate_serving_tp2d(args.devices, args.batch_mult))
    if args.config in ("serving-cluster", "all"):
        emit(validate_serving_cluster(args.devices, args.batch_mult))
    if args.config in ("serving-host", "all"):
        emit(validate_serving_host(args.devices, args.batch_mult))
    if args.config in ("serving-lowbit", "all"):
        emit(validate_serving_lowbit(args.devices, args.batch_mult))
    if args.config in ("serving-treespec", "all"):
        emit(validate_serving_treespec(args.devices, args.batch_mult))
    if args.config in ("serving-async", "all"):
        emit(validate_serving_async(args.devices, args.batch_mult))
    if args.config in ("serving-adapters", "all"):
        emit(validate_serving_adapters(args.devices, args.batch_mult))
    if args.config in ("serving-wal", "all"):
        emit(validate_serving_wal(args.devices, args.batch_mult))
    ok = True
    for r in rows:
        ok = ok and (r.get("fits_v5p") is not False)
    return 0 if ok else 2


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--devices", type=int, default=16,
                    help="virtual chips (v5p-32 slice = 16 chips)")
    ap.add_argument("--config",
                    choices=["7b", "13b", "13b-long", "moe", "moe-pp",
                             "serving", "serving-tp", "serving-tp2d",
                             "serving-cluster",
                             "serving-host", "serving-lowbit",
                             "serving-treespec",
                             "serving-async", "serving-adapters",
                             "serving-wal", "all"],
                    default="all")
    ap.add_argument("--batch-mult", type=int, default=1,
                    help="scale the recipe batch to probe HBM headroom")
    ap.add_argument("--schedule", default="zero_bubble",
                    choices=["gpipe", "1f1b", "zero_bubble", "interleave",
                             "interleave_1f1b", "vpp_zb"],
                    help="13b pipeline schedule (VERDICT r4 #6 residency)")
    ap.add_argument("--num-chunks", type=int, default=1,
                    help="VPP chunks for the interleave / interleave_1f1b / "
                         "vpp_zb schedules (the PERF_NOTES sweep used 2; "
                         "1 degenerates to a non-interleaved program)")
    ap.add_argument("--_child", action="store_true")
    args = ap.parse_args()
    if args._child:
        import jax
        jax.config.update("jax_platforms", "cpu")
        # persistent compilation cache (VERDICT r5 top_next — ops): the
        # north-star configs take minutes of XLA compile each; caching
        # under artifacts/xla_cache/ makes re-validation after an
        # unrelated CHECK-crash (or a fresh round) near-instant
        sys.path.insert(0, os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        import bench
        bench.enable_persistent_compilation_cache()
        rc = _impl(args)
        sys.stdout.flush()
        os._exit(rc)
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # hand the child the shared persistent-compile cache (bench.py and
    # tools/tpu_watch.sh point at the same artifacts/xla_cache/)
    env.setdefault(
        "JAX_COMPILATION_CACHE_DIR",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "artifacts", "xla_cache"))
    # all-reduce-promotion: XLA's CPU pass CHECK-crashes ("Invalid binary
    # instruction opcode copy", hlo_instruction.cc:1585) cloning some
    # GSPMD-inserted bf16 all-reduces in the interleave-schedule AD graph;
    # bf16 all-reduces compile and run correctly on CPU without the pass.
    # Companion workaround for the SAME bug: pp_spmd._psum_act upcasts
    # the EXPLICIT activation psums to f32 on CPU meshes (GSPMD-inserted
    # all-reduces never route through it, hence this flag) — see its
    # docstring for the retirement order when upstream fixes the CHECK
    env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                        " --xla_disable_hlo_passes=all-reduce-promotion"
                        f" --xla_force_host_platform_device_count="
                        f"{args.devices}")
    # repo root only: the ambient PYTHONPATH carries a sitecustomize that
    # pins a TPU tunnel whose init can hang
    env["PYTHONPATH"] = os.path.dirname(
        os.path.dirname(os.path.abspath(__file__)))
    proc = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--_child",
         "--devices", str(args.devices), "--config", args.config,
         "--batch-mult", str(args.batch_mult),
         "--schedule", args.schedule,
         "--num-chunks", str(args.num_chunks)],
        env=env, timeout=3600,
        cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
    sys.exit(proc.returncode)


if __name__ == "__main__":
    main()
