"""Standalone serving/decode tier bench (VERDICT r4 missing #1 / weak #7).

The driver bench's decode extras share one watchdog with the train
headline; on a slow-compile day the extras die and the decode tiers
stay null (they have been null in every round so far). This tool measures
ONLY the decode tiers — fp bf16, the paged continuous-batching engine
(with the fused-kernel speedup rider), the prefix-cache +
chunked-prefill shared-system-prompt engine, int8 weight-only (dense),
and the LOW-BIT PAGED tiers (per-group-int4 weights and
int8-weight+int8-KV on the serving engine itself — ISSUE 11) — with the
whole budget to itself, on freshly initialized weights (decode
throughput does not depend on weight values).

Prints one JSON line:
  {"decode_tokens_per_sec": ..., "decode_paged_tokens_per_sec": ...,
   "decode_prefix_tokens_per_sec": ..., "decode_sched_tokens_per_sec": ...,
   "decode_sched_step_ms": {"p50_step_ms": ..., "p99_step_ms": ...},
   "decode_spec_tokens_per_sec": ...,
   "decode_spec_acceptance": {"acceptance_rate": ...,
                              "nonrepetitive": {...}, ...},
   "decode_treespec_tokens_per_sec": ...,
   "decode_treespec_stats": {"tree_width": ..., "depth": ...,
                             "mean_accepted_path": ..., ...},
   "decode_tp_tokens_per_sec": ...,
   "decode_tp_scaling": {"tp": 4, "vs_single_chip": ...},
   "decode_int8_tokens_per_sec": ..., "decode_int4_tokens_per_sec": ...,
   "decode_w8kv8_tokens_per_sec": ..., "device": ...,
   "ratios_vs_fp": {...}}

Run on the live chip (axon tunnel) or CPU (tier RATIOS still order the
quantization story when no silicon is available — VERDICT r4 weak #7).
"""
from __future__ import annotations

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def main():
    import numpy as np
    import jax
    import jax.numpy as jnp

    t_start = time.perf_counter()
    budget = int(os.environ.get("PADDLE_TPU_BENCH_TIMEOUT", "2400"))

    import bench as bench_mod
    # persistent XLA compilation cache (artifacts/xla_cache/): the
    # decode tiers are MANY small programs (bucketed chunk/verify grid,
    # per-tier decode loops) — exactly what dies to recompiles when a
    # tunnel window is short. Cached compiles let one window bank every
    # tier and the next window re-load them.
    bench_mod.enable_persistent_compilation_cache()
    from paddle_tpu.models import generate as gen
    from paddle_tpu.models import train

    cfg, seq, _batch = bench_mod.pick_config()
    dev = jax.devices()[0]
    on_tpu = dev.platform == "tpu"

    params = jax.jit(
        lambda k: train.init_train_state(k, cfg).params)(jax.random.key(0))

    db, dp_len, dnew = (8, 128, 64) if on_tpu else (2, 8, 8)
    prompt = jnp.asarray(np.random.default_rng(1).integers(
        0, cfg.vocab_size, (db, dp_len)), jnp.int32)

    def decode_rate(pp, kv=None):
        def make(n):
            f = jax.jit(lambda pr: gen.generate(
                pp, pr, cfg, max_new_tokens=n, temperature=0.0,
                kv_cache_dtype=kv))
            np.asarray(f(prompt))              # compile + host fence
            return f

        def timed(f):
            best = float("inf")
            for _ in range(3):
                t0 = time.perf_counter()
                np.asarray(f(prompt))          # host-transfer fence
                best = min(best, time.perf_counter() - t0)
            return best
        g_full, g_one = make(dnew), make(1)
        ddt = timed(g_full) - timed(g_one)
        if ddt <= 0:   # tiny CPU smoke configs: noise swamps the delta
            ddt = timed(g_full)
        return round(db * (dnew - 1) / ddt, 2)

    def remaining():
        return budget - (time.perf_counter() - t_start)

    out = {"device": dev.device_kind if on_tpu else dev.platform,
           "batch": db, "prompt_len": dp_len, "new_tokens": dnew,
           "params": cfg.num_params()}
    tiers = {}

    def run_tier(tag, fn):
        if remaining() < 60:
            print(f"{tag} skipped: {remaining():.0f}s left",
                  file=sys.stderr)
            return
        t0 = time.perf_counter()
        try:
            tiers[tag] = fn()
            print(f"{tag}: {tiers[tag]} tok/s "
                  f"({time.perf_counter() - t0:.0f}s incl. compile)",
                  file=sys.stderr)
        except Exception as e:  # noqa: BLE001 — a tier failure must not
            # kill the tiers already measured
            print(f"{tag} failed: {type(e).__name__}: {e}"[:400],
                  file=sys.stderr)

    run_tier("decode_tokens_per_sec", lambda: decode_rate(params))

    # shared workload with bench.py's tier (same mix, oversubscription,
    # page-size rule) so the two decode_paged sources stay comparable;
    # the fused-kernel speedup rider (ISSUE 11 — per-step ms unfused vs
    # fused + the ratio) rides the record next to the number it explains
    def _paged():
        tps, fused = bench_mod.paged_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        if fused:
            out["decode_fused_speedup"] = fused
        return tps
    run_tier("decode_paged_tokens_per_sec", _paged)
    # shared-system-prompt workload (prefix cache + chunked prefill),
    # also shared with bench.py so both sources stay comparable
    run_tier("decode_prefix_tokens_per_sec",
             lambda: bench_mod.prefix_decode_tier(
                 params, cfg, db, dp_len, dnew, on_tpu))

    # SLO-scheduler control plane (ISSUE 4): oversubscribed
    # two-priority bursty workload with preempt/evict/resume under a
    # token-budgeted step planner — also shared with bench.py; the
    # p50/p99 step-latency dict rides the record separately, and the
    # ISSUE 12 overlap rider (sync vs double-buffered step ms +
    # host_overhead_fraction) rides next to it
    def _sched():
        tps, lat, ov, dur, trc = bench_mod.sched_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_sched_step_ms"] = lat
        if ov:
            out["decode_overlap_speedup"] = ov
        if dur:
            # durability rider (ISSUE 15): WAL fsync-ladder overhead
            # vs the journal-off baseline on the same workload
            out["decode_durability_overhead"] = dur
        if trc:
            # trace rider (ISSUE 16): request tracing ON vs the plain
            # run — the measured price of the observability switch
            out["decode_trace_overhead"] = trc
        return tps
    run_tier("decode_sched_tokens_per_sec", _sched)

    # speculative decoding (ISSUE 5): n-gram draft + batched verify on
    # a repetitive workload — acceptance rate rides the record next to
    # the throughput it explains
    def _spec():
        tps, acc = bench_mod.spec_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_spec_acceptance"] = acc
        return tps
    run_tier("decode_spec_tokens_per_sec", _spec)

    # model-based draft + tree speculation (ISSUE 20): truncated-layer
    # draft model + one-forward tree verify on the NON-repetitive
    # text-mode trace — the {tree_width, depth, mean_accepted_path}
    # rider rides next to the throughput it explains
    def _treespec():
        tps, stats = bench_mod.treespec_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_treespec_stats"] = stats
        return tps
    run_tier("decode_treespec_tokens_per_sec", _treespec)

    # tensor-parallel paged serving (ISSUE 7): the mixed-length paged
    # workload over a tp=4 serving mesh, with the aggregate-vs-single-
    # chip scaling factor riding the record (needs >= 4 devices — a
    # single-chip tunnel records the tier null, honestly)
    def _tp():
        tps = bench_mod.tp_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        paged = tiers.get("decode_paged_tokens_per_sec")
        out["decode_tp_scaling"] = {
            "tp": 4,
            "vs_single_chip": round(tps / paged, 3) if paged else None}
        return tps
    run_tier("decode_tp_tokens_per_sec", _tp)

    # 2-D tp x dp serving mesh (ISSUE 17): the same workload with the
    # decode batch split over a dp axis on top of tp=2 — db rows per
    # dp shard; the vs-1-D-tp ratio rides the record (needs >= 4
    # devices — a single-chip tunnel records the tier null, honestly)
    def _tp2d():
        tps = bench_mod.tp2d_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        tp1d = tiers.get("decode_tp_tokens_per_sec")
        out["decode_tp2d_scaling"] = {
            "tp": 2, "dp": 2,
            "vs_1d_tp": round(tps / tp1d, 3) if tp1d else None}
        return tps
    run_tier("decode_tp2d_tokens_per_sec", _tp2d)

    # disaggregated serving cluster (ISSUE 9): two replicas behind the
    # prefix-affinity router on a shared-prefix tenant workload — the
    # cluster-vs-single-engine ratio rides the record next to the
    # throughput it explains, same contract as the other riders
    def _cluster():
        tps, scaling = bench_mod.cluster_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_cluster_scaling"] = scaling
        # multi-process overhead rider (ISSUE 19): the same shape as a
        # process tree behind the socket RPC control plane — best
        # effort, the in-process cluster number stands either way
        try:
            out["decode_multiproc_overhead"] = (
                bench_mod.multiproc_overhead_tier(on_tpu))
        except Exception as e:
            print(f"multiproc overhead rider failed: "
                  f"{type(e).__name__}: {e}"[:300], file=sys.stderr)
        return tps
    run_tier("decode_cluster_tokens_per_sec", _cluster)

    # hierarchical KV host tier (ISSUE 10): the bursty preempt workload
    # with swap-out/swap-in resume — swap-in latency p50 and the
    # vs-replay-prefill ratio ride the record next to the throughput
    def _offload():
        tps, resume = bench_mod.offload_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_offload_resume"] = resume
        return tps
    run_tier("decode_offload_tokens_per_sec", _offload)

    # goodput-under-SLO (ISSUE 13): the trace-driven traffic harness
    # against the autoscaling cluster — deadline-met fraction, p99
    # TTFT and the autoscale event counts ride the record next to the
    # goodput they explain, same contract as the other riders
    def _slo():
        tps, metrics = bench_mod.slo_goodput_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_slo_metrics"] = metrics
        return tps
    run_tier("decode_slo_goodput_tokens_per_sec", _slo)

    # multi-tenant adapter plane (ISSUE 14): many LoRA variants through
    # one engine's slot pool vs the single-merged-model deployment —
    # the adapter-density rider (slot hits, demote/promote churn, the
    # vs-merged ratio) rides next to the throughput it explains
    def _multilora():
        tps, density = bench_mod.multilora_decode_tier(
            params, cfg, db, dp_len, dnew, on_tpu)
        out["decode_multilora_density"] = density
        return tps
    run_tier("decode_multilora_tokens_per_sec", _multilora)
    int8_p = {}

    def _int8():
        int8_p["p"] = gen.quantize_weights(params, cfg)
        return decode_rate(int8_p["p"])
    run_tier("decode_int8_tokens_per_sec", _int8)
    # low-bit PAGED-ENGINE tiers (ISSUE 11): int4 weights and w8/kv8 on
    # the serving tower itself (same workload as decode_paged — the
    # ratio against it IS the low-bit bandwidth win); these two slots
    # had never produced a number while they aliased the dense path
    run_tier("decode_int4_tokens_per_sec",
             lambda: bench_mod.lowbit_decode_tier(
                 params, cfg, db, dp_len, dnew, on_tpu, 4))
    run_tier("decode_w8kv8_tokens_per_sec",
             lambda: bench_mod.lowbit_decode_tier(
                 params, cfg, db, dp_len, dnew, on_tpu, 8,
                 kv_cache_dtype="int8"))

    out.update({k: tiers.get(k) for k in (
        "decode_tokens_per_sec", "decode_paged_tokens_per_sec",
        "decode_prefix_tokens_per_sec", "decode_sched_tokens_per_sec",
        "decode_spec_tokens_per_sec",
        "decode_treespec_tokens_per_sec", "decode_tp_tokens_per_sec",
        "decode_tp2d_tokens_per_sec",
        "decode_cluster_tokens_per_sec",
        "decode_offload_tokens_per_sec",
        "decode_slo_goodput_tokens_per_sec",
        "decode_multilora_tokens_per_sec",
        "decode_int8_tokens_per_sec", "decode_int4_tokens_per_sec",
        "decode_w8kv8_tokens_per_sec")})
    fp = tiers.get("decode_tokens_per_sec")
    if fp:
        out["ratios_vs_fp"] = {
            k.replace("_tokens_per_sec", ""): round(v / fp, 3)
            for k, v in tiers.items() if v}
    print(json.dumps(out))
    sys.stdout.flush()
    os._exit(0)   # skip hanging plugin destructors at interpreter exit


if __name__ == "__main__":
    main()
