"""One-off perf sweep for the bench config on the real chip.

Runs each variant in a subprocess (isolates OOM/compile failures), prints
tokens/s + MFU per variant. Not part of the driver flow — a tuning tool.
"""
import json
import os
import subprocess
import sys
import time

CHILD = r"""
import time, json, os, sys
import numpy as np
import jax, jax.numpy as jnp
from paddle_tpu.models import llama, train

variant = json.loads(os.environ["SWEEP_VARIANT"])
cfg = llama.LlamaConfig(
    vocab_size=32000, hidden_size=1536, intermediate_size=4096,
    num_layers=20, num_heads=12, num_kv_heads=12, max_seq_len=4096,
    dtype=jnp.bfloat16, remat=variant.get("remat", True),
    remat_policy=variant.get("policy", "nothing"),
    fused_kernels=variant.get("fused", "xla"))
batch = variant.get("batch", 4)
seq = 4096
step = train.make_train_step(cfg, seq_chunk=variant.get("seq_chunk", 512))
state = jax.jit(lambda k: train.init_train_state(k, cfg))(jax.random.key(0))
tokens = jnp.asarray(np.random.default_rng(0).integers(
    0, cfg.vocab_size, (batch, seq)), jnp.int32)
state, m = step(state, tokens); float(m["loss"])
state, m = step(state, tokens); float(m["loss"])
iters = 10
t0 = time.perf_counter()
for _ in range(iters):
    state, m = step(state, tokens)
float(m["loss"])
dt = (time.perf_counter() - t0) / iters
tps = batch * seq / dt
mfu = tps * cfg.flops_per_token(seq) / 197e12
print("SWEEP_RESULT " + json.dumps(
    {"variant": variant, "tps": round(tps, 1), "mfu": round(mfu, 4)}))
sys.stdout.flush()
os._exit(0)
"""

VARIANTS = [
    {"name": "base_b4_nothing", "batch": 4, "policy": "nothing"},
    {"name": "b4_attn", "batch": 4, "policy": "attn"},
    {"name": "b8_nothing", "batch": 8, "policy": "nothing"},
    {"name": "b8_attn", "batch": 8, "policy": "attn"},
    {"name": "b4_dots", "batch": 4, "policy": "dots"},
    {"name": "b4_chunk1024", "batch": 4, "policy": "nothing",
     "seq_chunk": 1024},
    {"name": "b4_pallas", "batch": 4, "policy": "nothing", "fused": "auto"},
    {"name": "b4_attn_pallas", "batch": 4, "policy": "attn",
     "fused": "auto"},
]


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WINNER_PATH = os.path.join(_REPO, "PERF_WINNER.json")
BASE_NAME = "base_b4_nothing"
ADOPT_MARGIN = 1.02     # flip the bench config only for a >2% win


def _record_winner(results):
    """If a measured variant beats the base by the adoption margin,
    write PERF_WINNER.json so bench.py's pick_config applies it on the
    next (e.g. driver end-of-round) run — no manual flip needed."""
    by_name = {r["variant"]["name"]: r for r in results}
    base = by_name.get(BASE_NAME)
    if base is None or not results:
        return
    best = max(results, key=lambda r: r["tps"])
    if best["variant"]["name"] == BASE_NAME or \
            best["tps"] < base["tps"] * ADOPT_MARGIN:
        # base (still) wins: clear any stale winner so bench reverts
        if os.path.exists(WINNER_PATH):
            os.remove(WINNER_PATH)
            print("SWEEP_WINNER cleared (base config wins)")
        return
    rec = {"variant": best["variant"], "tps": best["tps"],
           "mfu": best["mfu"], "base_tps": base["tps"],
           "gain": round(best["tps"] / base["tps"] - 1, 4),
           "recorded_unix": time.time(),
           "recorded_at": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                        time.gmtime())}
    # atomic: the driver's bench may read concurrently with this write
    tmp = WINNER_PATH + ".tmp"
    with open(tmp, "w") as f:
        json.dump(rec, f, indent=1)
    os.replace(tmp, WINNER_PATH)
    print("SWEEP_WINNER " + json.dumps(rec))


def main():
    names = sys.argv[1:]
    results = []
    for v in VARIANTS:
        if names and v["name"] not in names:
            continue
        env = dict(os.environ)
        env["SWEEP_VARIANT"] = json.dumps(v)
        try:
            proc = subprocess.run([sys.executable, "-c", CHILD], env=env,
                                  stdout=subprocess.PIPE,
                                  stderr=subprocess.STDOUT, text=True,
                                  timeout=600)
            parsed = None
            for line in proc.stdout.splitlines():
                if line.startswith("SWEEP_RESULT"):
                    try:
                        # runtime log writes can interleave into stdout;
                        # a torn line must not abort the whole sweep
                        parsed = json.loads(line[len("SWEEP_RESULT "):])
                    except ValueError:
                        continue
                    print(line)
                    results.append(parsed)
                    break
            if parsed is None:
                tail = " | ".join(proc.stdout.strip().splitlines()[-3:])
                print(f"SWEEP_FAIL {v['name']}: {tail[-300:]}")
        except subprocess.TimeoutExpired:
            print(f"SWEEP_TIMEOUT {v['name']}")
        sys.stdout.flush()
    if not names:                 # only a FULL sweep may adopt a winner
        _record_winner(results)


if __name__ == "__main__":
    main()
