"""Op-parity audit vs the reference op registry.

Parses the reference's op YAML (reference: paddle/phi/ops/yaml/ops.yaml —
472 ops — plus sparse_ops.yaml) and checks each op name against this
framework's public surface (paddle_tpu.*, Tensor methods, nn.functional,
linalg/fft/signal/sparse/incubate namespaces, plus a small alias table for
ops whose python-API name differs from the kernel name, mirroring
op_compat.yaml).

Usage:
    python tools/op_coverage.py [--ref /root/reference] [--write]

--write regenerates OPS_COVERAGE.md at the repo root.
"""
from __future__ import annotations

import argparse
import os
import re
import sys

# kernel-name -> where the capability actually lives in this framework (or
# in the reference python API). Mirrors op_compat.yaml renames plus
# capability-level equivalences (optimizer update ops are Optimizer
# classes, c_* collectives are paddle_tpu.distributed, etc.)
ALIASES = {
    # optimizer update kernels == optimizer classes
    "adadelta_": "optimizer.Adadelta", "adagrad_": "optimizer.Adagrad",
    "adam_": "optimizer.Adam", "adamax_": "optimizer.Adamax",
    "adamw_": "optimizer.AdamW", "lamb_": "optimizer.Lamb",
    "momentum_": "optimizer.Momentum", "sgd_": "optimizer.SGD",
    "rmsprop_": "optimizer.RMSProp", "lars_momentum": "optimizer.Lars",
    "merged_adam_": "optimizer.Adam", "merged_momentum_": "optimizer.Momentum",
    "dgc_momentum": "fleet.meta_optimizers.DGCMomentumOptimizer",
    "dgc": "fleet.meta_optimizers.dgc_optimizer.dgc_compress",
    "sparse_momentum": None,
    "distributed_fused_lamb_init": "incubate.DistributedFusedLamb",
    # elementwise / math renames
    "elementwise_pow": "pow", "divide": "divide", "fmin": "fmin",
    "fmax": "fmax", "grad_add": "add", "remainder": "remainder",
    "share_buffer": "Tensor.detach", "share_data": "Tensor.detach",
    "assign": "assign", "assign_out_": "assign",
    "assign_value": "assign",
    "assign_pos": "distributed.utils.moe_utils.assign_pos",
    "full_batch_size_like": "full", "fill": "full",
    "fill_diagonal": "Tensor.fill_diagonal_",
    "fill_diagonal_tensor": "Tensor.fill_diagonal_",
    "flatten2": "flatten", "squeeze2": "squeeze", "unsqueeze2": "unsqueeze",
    "reshape2": "reshape", "transpose2": "transpose",
    "reduce_sum": "sum", "reduce_mean": "mean", "reduce_max": "max",
    "reduce_min": "min", "reduce_prod": "prod", "reduce_all": "all",
    "reduce_any": "any",
    "arg_max": "argmax", "arg_min": "argmin",
    "top_k": "topk", "top_k_v2": "topk",
    "one_hot": "nn.functional.one_hot",
    "matmul_v2": "matmul", "mul": "matmul", "bmm": "bmm",
    "elementwise_add": "add", "elementwise_sub": "subtract",
    "elementwise_mul": "multiply", "elementwise_div": "divide",
    "elementwise_max": "maximum", "elementwise_min": "minimum",
    "elementwise_mod": "remainder", "elementwise_floordiv": "floor_divide",
    "hard_swish": "nn.functional.hardswish",
    "hard_sigmoid": "nn.functional.hardsigmoid",
    "hard_shrink": "nn.functional.hardshrink",
    "hard_tanh": "nn.functional.hardtanh",
    "brelu": "nn.functional.hardtanh",
    "soft_relu": "nn.functional.softplus",
    "softmax_with_cross_entropy": "nn.functional.cross_entropy",
    "cross_entropy_with_softmax": "nn.functional.cross_entropy",
    "sigmoid_cross_entropy_with_logits":
        "nn.functional.binary_cross_entropy_with_logits",
    "c_softmax_with_cross_entropy": "fleet mpu ParallelCrossEntropy",
    "c_softmax_with_multi_label_cross_entropy": None,
    "softmax_v2": "nn.functional.softmax",
    "depthwise_conv2d": "nn.functional.conv2d",
    "conv2d_transpose_bias": "nn.functional.conv2d_transpose",
    "batch_norm_": "nn.functional.batch_norm",
    "sync_batch_norm_": "nn.SyncBatchNorm",
    "pool2d": "nn.functional.max_pool2d/avg_pool2d",
    "pool3d": "nn.functional.max_pool3d/avg_pool3d",
    "max_pool2d_with_index": "nn.functional.max_pool2d",
    "max_pool3d_with_index": "nn.functional.max_pool3d",
    "relu6": "nn.functional.relu6",
    "swish": "nn.functional.swish", "mish": "nn.functional.mish",
    "seed": "seed",
    "dropout_nd": "nn.functional.dropout",
    "fused_softmax_mask": "incubate.softmax_mask_fuse",
    "fused_softmax_mask_upper_triangle": "incubate.softmax_mask_fuse",
    "flash_attn": "nn.functional.flash_attention",
    "flash_attn_unpadded": "nn.functional.flash_attn_unpadded",
    "flash_attn_varlen_qkvpacked": "nn.functional.flash_attn_unpadded",
    "flash_attn_qkvpacked": "nn.functional.flash_attention",
    "flashmask_attention": "nn.functional.flash_attention",
    "memcpy_d2h": "Tensor.cpu", "memcpy_h2d": "Tensor.cuda",
    "memcpy": "Tensor.to", "npu_identity": None,
    "print": "static.Print", "py_func": "PyLayer",
    "einsum": "einsum",
    "embedding_grad_dense": "nn.functional.embedding",
    "c_embedding": "fleet mpu VocabParallelEmbedding",
    "cross_attention": None,
    "expand_v2": "expand", "expand_as_v2": "expand_as",
    "gaussian": "normal", "uniform": "uniform", "randint": "randint",
    "truncated_gaussian_random": "nn.initializer.TruncatedNormal",
    "exponential_": "Tensor.exponential_",
    "lookup_table_v2": "nn.functional.embedding",
    "squared_l2_norm": "norm",
    "fill_constant": "full", "fill_any_like": "full_like",
    "fill_any": "full",
    "size": "numel", "shape": "Tensor.shape",
    "slice": "slice", "strided_slice": "strided_slice",
    "set_value": "Tensor.__setitem__",
    "set_value_with_tensor": "Tensor.__setitem__",
    "tile": "tile", "unbind": "unbind", "unstack": "unstack",
    "viterbi_decode": "text.viterbi_decode",
    "pull_sparse_v2": "distributed.ps", "push_sparse_v2": "distributed.ps",
    "pull_box_sparse": "distributed.ps", "push_box_sparse": "distributed.ps",
    "pull_gpups_sparse": "distributed.ps",
    "push_gpups_sparse": "distributed.ps",
    "pull_dense": "distributed.ps", "push_dense": "distributed.ps",
    "update_loss_scaling_": "amp.GradScaler",
    "check_finite_and_unscale_": "amp.GradScaler",
    "get_tensor_from_selected_rows": None,
    "limit_by_capacity": "incubate moe", "prune_gate_by_capacity":
        "incubate moe", "random_routing": "incubate moe",
    "number_count": "incubate moe",
    "global_scatter": "distributed.utils.moe_utils.global_scatter",
    "global_gather": "distributed.utils.moe_utils.global_gather",
    "identity_loss": "Tensor.mean",
    "rrelu": "nn.functional.rrelu",
    "moving_average_abs_max_scale": "quantization observers",
    "quantize_linear": "quantization.quantize_linear",
    "dequantize_linear": "quantization.dequantize_linear",
    "fake_quantize_abs_max": "quantization fake quant",
    "fake_quantize_range_abs_max": "quantization fake quant",
    "fake_quantize_moving_average_abs_max": "quantization fake quant",
    "fake_quantize_dequantize_abs_max": "quantization fake quant",
    "fake_quantize_dequantize_moving_average_abs_max":
        "quantization fake quant",
    "fake_channel_wise_quantize_abs_max": "quantization fake quant",
    "fake_channel_wise_quantize_dequantize_abs_max":
        "quantization fake quant",
    "fake_channel_wise_dequantize_max_abs": "quantization fake quant",
    "fake_dequantize_max_abs": "quantization fake quant",
    "straight_through_estimator_grad": "quantization STE",
    # verified equivalents (python API name differs from kernel name)
    "bce_loss": "nn.functional.binary_cross_entropy",
    "logsigmoid": "nn.functional.log_sigmoid",
    "tanh_shrink": "nn.functional.tanhshrink",
    "kldiv_loss": "nn.functional.kl_div",
    "huber_loss": "nn.functional.smooth_l1_loss",
    "hinge_loss": "nn.functional.hinge_embedding_loss",
    "warpctc": "nn.functional.ctc_loss",
    "bicubic_interp": "nn.functional.interpolate",
    "bilinear_interp": "nn.functional.interpolate",
    "linear_interp": "nn.functional.interpolate",
    "nearest_interp": "nn.functional.interpolate",
    "trilinear_interp": "nn.functional.interpolate",
    "pad3d": "nn.functional.pad",
    "p_norm": "linalg.norm", "frobenius_norm": "linalg.norm",
    "l1_norm": "linalg.norm", "squared_l2_norm": "linalg.norm",
    "mean_all": "mean", "split_with_num": "split",
    "full_int_array": "full", "full_with_tensor": "full",
    "data": "static.data",
    "dirichlet": "distribution.Dirichlet",
    "auc": "metric.Auc", "accuracy": "metric.Accuracy",
    "accuracy_check": "amp.debugging accuracy_check/compare_accuracy",
    "deformable_conv": "vision.ops deform_conv2d",
    "shuffle_channel": "channel_shuffle",
    "crf_decoding": "text.viterbi_decode",
    "reindex_graph": "incubate.graph_reindex",
    "multiclass_nms3": "vision.ops multiclass_nms",
    "spectral_norm": "nn.utils spectral_norm (hook reparam)",
    "check_numerics": "amp.debugging.check_numerics",
    "enable_check_model_nan_inf": "amp.debugging",
    "disable_check_model_nan_inf": "amp.debugging",
    "view_dtype": "Tensor.view", "view_shape": "Tensor.view",
    "view_slice": "Tensor.view",
    "copy_to": "Tensor.to",
    "rnn": "nn.SimpleRNN/LSTM/GRU", "lstm": "nn.LSTM",
    "cudnn_lstm": "nn.LSTM", "gru": "nn.GRU", "gru_unit": "nn.GRUCell",
    "attention_lstm": None,
    "matrix_rank_tol": "linalg.matrix_rank",
    "matrix_rank_atol_rtol": "linalg.matrix_rank",
    "repeat_interleave_with_tensor_index": "repeat_interleave",
    "c_allgather": "distributed.all_gather",
    "c_allreduce_max": "distributed.all_reduce",
    "c_allreduce_min": "distributed.all_reduce",
    "c_allreduce_prod": "distributed.all_reduce",
    "c_allreduce_sum": "distributed.all_reduce",
    "c_broadcast": "distributed.broadcast",
    "c_concat": "distributed.all_gather",
    "c_identity": "fleet mpu (GSPMD identity)",
    "c_reduce_sum": "distributed.reduce",
    "c_scatter": "distributed.scatter",
    "mp_allreduce_sum": "distributed.all_reduce",
    "partial_allgather": "distributed.all_gather",
    "fft_c2c": "fft.fft", "fft_c2r": "fft.irfft", "fft_r2c": "fft.rfft",
    "gaussian_inplace": "Tensor.normal_",
    "uniform_inplace": "Tensor.uniform_",
    "uniform_random_batch_size_like": "uniform",
    "beam_search": "models.generate + gather_tree",
    "trans_layout": "transpose",
    "index_select_strided": "index_select",
    "im2sequence": "nn.functional.unfold",
    "set": "Tensor.__setitem__",
    "grid_sample": "nn.functional.grid_sample",
    "segment_pool": "geometric.segment_sum/mean/max/min",
    "graph_send_recv": "geometric.send_u_recv",
    "graph_send_ue_recv": "geometric.send_ue_recv",
    "send_u_recv": "geometric.send_u_recv",
    "send_ue_recv": "geometric.send_ue_recv",
    "send_uv": "geometric.send_uv",
    "weight_quantize": "nn.quant.weight_quantize",
    "weight_dequantize": "nn.quant.weight_dequantize",
    "weight_only_linear": "nn.quant.weight_only_linear",
    "llm_int8_linear": "nn.quant.llm_int8_linear",
    "apply_per_channel_scale": "nn.quant (dequant fused in matmul)",
    "dequantize_abs_max": "nn.quant.weight_dequantize",
    "fractional_max_pool2d": "nn.functional.fractional_max_pool2d",
    "fractional_max_pool3d": "nn.functional.fractional_max_pool3d",
    "unpool": "nn.functional.max_unpool2d",
    "unpool3d": "nn.functional.max_unpool3d",
    "lp_pool2d": "nn.functional.lp_pool2d",
    "margin_cross_entropy": "nn.functional.margin_cross_entropy",
    "gather_tree": "gather_tree", "sequence_mask": "sequence_mask",
    "top_p_sampling": "top_p_sampling",
    "clip_by_norm": "clip_by_norm",
    "warprnnt": "nn.functional.rnnt_loss (lax.scan forward-DP)",
    "merge_selected_rows": "sparse.coalesce (duplicate-row merge)",
    "dgc_clip_by_norm": "DGCMomentumOptimizer(grad_clip=...) n^-0.5 scaling",
    "multi_dot": "linalg.multi_dot", "lu_unpack": "linalg.lu_unpack",
    "edit_distance": "edit_distance",
    "fused_batch_norm_act": "nn.functional.batch_norm (XLA fuses act)",
    "fused_bn_add_activation": "nn.functional.batch_norm (XLA fuses)",
    "fused_softmax_mask_upper_triangle": "incubate.softmax_mask_fuse",
    "sparse_attention": "nn.functional.flash_attention",
    "memory_efficient_attention": "nn.functional.flash_attention",
    "calc_reduced_attn_scores": None,
    "depthwise_conv2d_transpose": "nn.functional.conv2d_transpose",
    "asgd_": "optimizer.ASGD", "nadam_": "optimizer.NAdam",
    "radam_": "optimizer.RAdam", "rprop_": "optimizer.Rprop",
    "decayed_adagrad": "optimizer.Adagrad",
    "average_accumulates_": "incubate.optimizer.ModelAverage",
    "affine_grid": "nn.functional.affine_grid",
    "nms": "vision.ops.nms",
    "assign_value_": "assign",
    "mean": "mean",
    # rec-sys / legacy incubate tier (incubate/layers.py; reference
    # python/paddle/incubate/layers/nn.py + kernel-only legacy ops)
    "shuffle_batch": "incubate.layers.shuffle_batch",
    "partial_concat": "incubate.layers.partial_concat",
    "partial_sum": "incubate.layers.partial_sum",
    "tdm_child": "incubate.layers.tdm_child",
    "tdm_sampler": "incubate.layers.tdm_sampler",
    "rank_attention": "incubate.layers.rank_attention",
    "batch_fc": "incubate.layers.batch_fc",
    "correlation": "incubate.layers.correlation",
    "affine_channel": "incubate.layers.affine_channel",
    "add_position_encoding": "incubate.layers.add_position_encoding",
    "bipartite_match": "incubate.layers.bipartite_match",
    "box_clip": "incubate.layers.box_clip",
    "ctc_align": "incubate.layers.ctc_align",
    "chunk_eval": "incubate.layers.chunk_eval",
    "im2sequence": "incubate.layers.im2sequence",
    "cvm": "static.nn.continuous_value_model",
    "sequence_conv": "static.nn.sequence_conv",
    "sequence_pool": "static.nn.sequence_pool",
    "ftrl": "incubate.optimizer.Ftrl",
    "detection_map": "incubate.layers.detection_map",
    "attention_lstm": "incubate.layers.attention_lstm",
    "match_matrix_tensor": "incubate.layers.match_matrix_tensor",
    "dpsgd": "incubate.optimizer.Dpsgd",
}

# ops that are deliberately out of scope on TPU (hardware-specific, legacy
# mobile/detection pipelines, or subsumed wholesale by XLA infrastructure)
OUT_OF_SCOPE = {
    # GPU/ASCEND-only runtime plumbing
    "c_comm_init_all", "comm_init_all", "c_gen_nccl_id", "gen_nccl_id",
    # detection-pipeline ops with NO modern python API in the reference
    # (train-pipeline internals the reference itself moved to legacy);
    # the implemented detection surface (roi/yolo/nms/box/proposals/
    # bipartite_match/box_clip) is classified directly or via ALIASES
    "density_prior_box", "locality_aware_nms", "mine_hard_examples",
    "polygon_box_transform", "retinanet_detection_output",
    "rpn_target_assign", "ssd_loss", "target_assign", "prroi_pool",
    # executor/stream plumbing subsumed by XLA program semantics
    "sync_calc_stream", "coalesce_tensor", "depend",
    "memcpy_d2h_multi_io", "beam_search_decode",

    # pyramid_hash: bespoke fused bloom-filter hash-embedding scheme with
    # no reimplementable python contract (de-scoped; the embedding
    # capability = nn.Embedding / PS sparse tables)
    "pyramid_hash",
    # GPU/NPU-runtime specific: flash-attention GPU scratch helper,
    # ascend-format identity
    "calc_reduced_attn_scores", "npu_identity",
    # sparse 3D point-cloud conv stack (GPU implicit-gemm; no TPU sparse
    # conv path — dense conv3d covers the capability)
    "conv3d_implicit_gemm", "maxpool", "fused_attention",
}


def parse_ops(yaml_path):
    ops = []
    with open(yaml_path) as f:
        for line in f:
            m = re.match(r"^- op\s*:\s*([A-Za-z0-9_]+)", line)
            if m:
                ops.append(m.group(1))
    return ops


def build_surface():
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu._core.tensor import Tensor
    names = set()
    for mod, prefix in [
            (paddle, ""), (F, "nn.functional."),
            (paddle.linalg, "linalg."), (paddle.nn, "nn."),
            (paddle.sparse, "sparse."), (paddle.fft, "fft."),
            (paddle.signal, "signal."), (paddle.incubate, "incubate."),
            (paddle.distributed, "distributed."),
            (paddle.vision.ops if hasattr(paddle.vision, "ops") else
             paddle.vision, "vision.ops."),
            (paddle.geometric, "geometric."),
            (paddle.nn.quant, "nn.quant.")]:
        for n in dir(mod):
            if not n.startswith("_"):
                names.add(n)
    try:
        import paddle_tpu.incubate.nn.functional as IF
        names |= {n for n in dir(IF) if not n.startswith("_")}
    except ImportError:
        pass
    for n in dir(Tensor):
        if not n.startswith("_"):
            names.add(n)
    return names


def check(op, surface):
    """-> (status, where). status: 'yes'|'alias'|'oos'|'no'."""
    if op in OUT_OF_SCOPE:
        return "oos", ""
    base = op[:-1] if op.endswith("_") else op
    for cand in (op, base):
        if cand in surface:
            return "yes", cand
    if op in ALIASES:
        tgt = ALIASES[op]
        return ("alias", tgt) if tgt else ("no", "")
    # inplace variants of existing ops (x_ -> x)
    return "no", ""


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--ref", default="/root/reference")
    ap.add_argument("--write", action="store_true")
    args = ap.parse_args()

    yam = os.path.join(args.ref, "paddle/phi/ops/yaml/ops.yaml")
    sparse_yam = os.path.join(args.ref, "paddle/phi/ops/yaml/sparse_ops.yaml")
    ops = parse_ops(yam)
    sparse_ops = parse_ops(sparse_yam) if os.path.exists(sparse_yam) else []
    surface = build_surface()

    rows, counts = [], {"yes": 0, "alias": 0, "oos": 0, "no": 0}
    for op in ops:
        st, where = check(op, surface)
        counts[st] += 1
        rows.append((op, st, where))
    sparse_rows = []
    sparse_surface = surface
    for op in sparse_ops:
        st, where = check(op, sparse_surface)
        sparse_rows.append((op, st, where))

    total = len(ops)
    covered = counts["yes"] + counts["alias"]
    in_scope = total - counts["oos"]
    missing = [r[0] for r in rows if r[1] == "no"]

    lines = []
    lines.append("# Op coverage vs reference `ops.yaml`\n")
    lines.append(f"Generated by `tools/op_coverage.py` "
                 f"(reference: paddle/phi/ops/yaml/ops.yaml, {total} ops; "
                 f"sparse_ops.yaml, {len(sparse_ops)} ops).\n")
    lines.append(f"| direct | alias/equivalent | out-of-scope (TPU) | "
                 f"missing | coverage (in-scope) |")
    lines.append("|---|---|---|---|---|")
    lines.append(f"| {counts['yes']} | {counts['alias']} | {counts['oos']} "
                 f"| {counts['no']} | {100.0 * covered / in_scope:.1f}% |\n")
    lines.append("`alias/equivalent` = python-API name differs from the "
                 "kernel name (op_compat.yaml renames) or the capability "
                 "lives in a subsystem (optimizer update kernels == "
                 "Optimizer classes, c_* collectives == "
                 "paddle_tpu.distributed, PS push/pull == distributed.ps). "
                 "`out-of-scope` = legacy detection pipeline / "
                 "GPU-runtime-specific ops.\n")
    lines.append("## Missing ops\n")
    for op in missing:
        lines.append(f"- `{op}`")
    lines.append("\n## Sparse ops (sparse_ops.yaml)\n")
    sp_cov = sum(1 for r in sparse_rows if r[1] in ("yes", "alias"))
    sp_oos = sum(1 for r in sparse_rows if r[1] == "oos")
    sp_missing = [r[0] for r in sparse_rows if r[1] == "no"]
    lines.append(
        f"{sp_cov}/{len(sparse_rows)} covered, {sp_oos} out-of-scope "
        "(GPU implicit-gemm 3D point-cloud conv stack); missing: " +
        (", ".join(f"`{m}`" for m in sp_missing) or "none") + "\n")
    lines.append("## Full table\n")
    lines.append("| op | status | where |")
    lines.append("|---|---|---|")
    for op, st, where in rows:
        lines.append(f"| {op} | {st} | {where} |")
    report = "\n".join(lines) + "\n"

    if args.write:
        out = os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "OPS_COVERAGE.md")
        with open(out, "w") as f:
            f.write(report)
        print(f"wrote {out}")
    print(f"direct={counts['yes']} alias={counts['alias']} "
          f"oos={counts['oos']} missing={counts['no']} "
          f"coverage={100.0 * covered / in_scope:.1f}%")
    if missing:
        print("missing:", " ".join(missing))
    return 0


if __name__ == "__main__":
    sys.exit(main())
