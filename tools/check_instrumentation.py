#!/usr/bin/env python
"""Instrumentation lint: the hot paths must keep their telemetry hooks.

The observability layer only attributes time if the hot-path modules
keep emitting their spans/metrics — a refactor that drops one hook
silently degrades every future BENCH_r*.json breakdown. This lint greps
each known hot-path module for its REQUIRED hook call sites and fails
if any went missing. Wired into the tier-1 run as a fast test
(tests/test_instrumentation_lint.py); runnable standalone:

    python tools/check_instrumentation.py
"""
from __future__ import annotations

import os
import sys

# module (repo-relative) -> [(required substring, min occurrences)]
REQUIRED = {
    "paddle_tpu/distributed/fleet/meta_parallel/pipeline_parallel.py": [
        ('_obs.span("PP.forward"', 1),
        ('_obs.span("PP.backward"', 1),
        ('_obs.span("PP.spmd.step"', 2),      # homogeneous + hetero
        ('_obs.span("PP.spmd.scatter"', 2),
        ("_obs.pp_step(", 3),                 # both SPMD paths + accum
    ],
    "paddle_tpu/inference/predictor.py": [
        ("_obs.predictor_run(", 1),
        ("_obs.active()", 1),
        # continuous-batching engine hot path: block-pool utilization
        # gauge + occupancy histogram (serving_step), admission and
        # eviction counters — the serving dashboard's inputs
        ("_obs.serving_step(", 1),
        ("_obs.serving_admitted(", 1),
        ("_obs.serving_retired(", 1),
        # prefix-cache hit/miss token counters (the live hit rate) and
        # the per-chunk prefill latency histogram (the engine's
        # per-step latency bound) — ISSUE 3's serving telemetry
        ("_obs.serving_prefix(", 1),
        ("_obs.serving_prefill_chunk(", 1),
        # preempt/resume lifecycle counters (ISSUE 4): evictions for
        # higher-priority admissions + the replay cost of resumes;
        # queued-request cancellations stay OUT of the eviction counter
        ("_obs.serving_preempted(", 1),
        ("_obs.serving_resumed(", 1),
        ("_obs.serving_cancelled(", 1),
        # speculative decoding (ISSUE 5): drafted/accepted/rollback
        # token counters + the per-step acceptance-rate histogram the
        # adaptive draft length is judged by — dropping this hook
        # blinds the decode_spec bench tier's acceptance record
        ("_obs.serving_spec_verify(", 1),
        # tensor-parallel serving (ISSUE 7): per-shard pool gauge every
        # step + the timed logits-collective probe — the dashboard's
        # only view of the tp collective bill
        ("_obs.serving_tp_step(", 1),
        ("_obs.serving_tp_logits_gather(", 1),
        # fault-injection sites (ISSUE 8): step execution + the
        # device->host transfers (decode AND spec-verify paths)
        ('_fault_point("decode_step")', 1),
        ('_fault_point("prefill_chunk")', 1),
        ('_fault_point("verify_step")', 1),
        ('_fault_point("transfer")', 2),
        # fused serving kernels (ISSUE 11): per-kernel host-timed step
        # latency on all three fused paths (decode / chunk / verify) —
        # the decode_fused_speedup rider's per-kernel breakdown
        ('_obs.serving_fused_latency("decode_rope_attn"', 1),
        ('_obs.serving_fused_latency("chunk_flash_attn"', 1),
        ('_obs.serving_fused_latency("verify_flash_attn"', 1),
        # async overlapped runtime (ISSUE 12): the dispatch/commit
        # seams — decode AND spec paths each fire both sites, so a
        # fault between program launch and host-state commit is
        # injectable (and chaos-soaked) on every step kind
        ('_fault_point("dispatch")', 2),
        ('_fault_point("commit")', 2),
        # sampled speculation (ISSUE 14): drafted/accepted counters +
        # the accept-rate histogram of the rejection-sampled verify
        # commit — the realized 1+k·rate speedup multiplier
        ("_obs.serving_sample_accept(", 1),
        # constrained decoding (ISSUE 14): mask-latency histogram +
        # violation-avoided counter on BOTH commit paths (the prefill
        # first token and the vectorized decode commit)
        ("_obs.serving_constrain(", 2),
        # request tracing (ISSUE 16): span-close sites on every engine
        # lifecycle edge — admission (swap-in AND replay paths), the
        # per-chunk prefill close, the per-row decode/verify closes,
        # preempt/swap-out, and the retire-side finish — dropping one
        # tears a hole in every TTFT breakdown
        ("_obs.serving_trace_admitted(", 2),
        ("_obs.serving_trace_span(", 5),
        ("_obs.serving_trace_finish(", 2),
        ("_obs.serving_trace_first_token(", 2),
        # 2-D serving mesh (ISSUE 17): per-dp-shard batch gauge on
        # both commit paths (decode AND spec verify) — the only view
        # of planner skew across the dp row blocks
        ("_obs.serving_dp_step(", 2),
        # model-based draft + tree speculation (ISSUE 20): the propose
        # counters (rows/drafted/catch-up tokens), the draft-pool
        # occupancy gauge pair, and the fence-anchored tree-verify span
        # with its path-length/acceptance histograms — the
        # decode_treespec bench tier's only inputs; plus the two new
        # fault sites, both firing BEFORE any state commits (a killed
        # propose or verify must leave lengths/pools untouched)
        ("_obs.serving_draft_propose(", 1),
        ("_obs.serving_draft_pool(", 1),
        ("_obs.serving_tree_verify(", 1),
        ('_fault_point("draft_propose")', 1),
        ('_fault_point("tree_verify")', 1),
    ],
    "paddle_tpu/observability/hooks.py": [
        # the ISSUE 20 hook families themselves: the predictor entries
        # above only prove the CALL sites exist — these prove the hook
        # layer still defines them (a hooks.py refactor that drops one
        # def would turn every call site into an AttributeError only
        # at serve time, with metrics enabled)
        ("def serving_draft_propose(", 1),
        ("def serving_draft_pool(", 1),
        ("def serving_tree_verify(", 1),
        ("serving_tree_path_len", 1),
        ("serving_tree_acceptance_rate", 1),
    ],
    "paddle_tpu/serving/scheduler.py": [
        # SLO-scheduler hot path (ISSUE 4): time-in-queue histogram on
        # every admission, per-class queue-depth gauges + the
        # budget-utilization gauge once per planned step
        ("_obs.serving_queue_wait(", 1),
        ("_obs.serving_sched_step(", 1),
        # async overlapped runtime (ISSUE 12): the per-step host-plane
        # attribution (host_overhead_fraction gauge + the
        # serving_sched_step_ms p99 source) and the idle-fence counter
        # of the busy-spin fix — the scoreboard the overlap refactor
        # is judged by
        ("_obs.serving_overlap_step(", 1),
        ("_obs.serving_sched_idle(", 1),
        # fault-injection site (ISSUE 8): the scheduler tick
        ('fault_point("sched_tick")', 1),
        # request tracing (ISSUE 16): trace minting at submission +
        # the queue-wait open on every (re)enqueue — the trace's first
        # edge; requeue re-attaches recovered/preempted handles so
        # cross-lifecycle stitching survives
        ("_obs.serving_trace_submit(", 1),
        ("_obs.serving_trace_enqueued(", 2),
    ],
    "paddle_tpu/serving/resilience.py": [
        # fault-tolerant serving (ISSUE 8): injected + real failure
        # counters (fire + catch sides), the recovery-latency
        # histogram, the degraded-mode gauge, the journal-size gauges
        # and both halves of the drain/restore pair — the supervisor
        # is the unit the multi-engine router will replicate, and a
        # blind supervisor cannot be routed around
        ("_obs.serving_fault(", 2),
        ("_obs.serving_fault_recovery(", 1),
        ("_obs.serving_degraded(", 2),        # ladder moves + dead
        ("_obs.serving_journal(", 1),
        ("_obs.serving_drain_checkpoint(", 1),
        ("_obs.serving_drain_restore(", 1),
        # durable journal plane (ISSUE 15): the cold-restart recovery
        # gauge/counters — a recovery that replays sessions invisibly
        # would make the crash-durability story unauditable
        ("_obs.serving_wal_recovery(", 1),
        # flight recorder (ISSUE 16): the per-tick ring append, the
        # dump counter on every black-box write, and the wal_replay
        # span on each recovered session — a crash with no flight dump
        # is an unauditable crash
        ("_obs.serving_flight_tick(", 1),
        ("_obs.serving_flight_dump(", 1),
        ("_obs.serving_trace_span(", 1),
    ],
    "paddle_tpu/serving/wal.py": [
        # durable WAL (ISSUE 15): per-record append counter/bytes/
        # latency, the fsync-ladder latency pair, and the incremental-
        # checkpoint triple — the fsync-policy overhead model's inputs
        # (PERF_NOTES 'Durability', decode_durability_overhead rider)
        ("_obs.serving_wal_append(", 1),
        ("_obs.serving_wal_fsync(", 1),
        ("_obs.serving_wal_checkpoint(", 1),
        # fault sites: append BEFORE the frame write, fsync before the
        # fsync, checkpoint before the file — none commits anything
        ('fault_point("wal_append")', 1),
        ('fault_point("wal_fsync")', 1),
        ('fault_point("checkpoint_write")', 1),
        # torn-write tamper: half a frame reaches disk and the 'process
        # dies' — recovery's tail truncation is what gets exercised
        ('tamper_point("wal_append")', 1),
    ],
    "paddle_tpu/serving/paged_cache.py": [
        # fault-injection sites (ISSUE 8): allocator alloc/free
        ('fault_point("alloc")', 1),
        ('fault_point("free")', 1),
        # fused page gather/scatter (ISSUE 11): the one donated move
        # program shared by defrag compaction and the direct handoff —
        # its latency histogram is the only visibility into device
        # page-move cost (the host-staged path's bytes counters don't
        # see it)
        ('_obs.serving_fused_latency("pool_move"', 1),
    ],
    "paddle_tpu/serving/traffic.py": [
        # trace-driven traffic harness (ISSUE 13): per-request TTFT +
        # deadline outcome, goodput/badput token split, and the
        # end-of-run summary gauges — the serving_slo_* family the
        # decode_slo_goodput bench tier records
        ("_obs.serving_slo_ttft(", 1),
        ("_obs.serving_slo_tokens(", 1),
        ("_obs.serving_slo_report(", 1),
    ],
    "paddle_tpu/serving/adapters.py": [
        # multi-tenant adapter plane (ISSUE 14): slot residency gauges
        # on every pool mutation, the install latency/bytes pair split
        # by source (fresh load vs host-store promote), the demote
        # counter+bytes of LRU slot reclaim, and the corrupt-payload
        # fallback counter — the serving_adapter_* family the
        # decode_multilora bench rider and the PERF_NOTES
        # adapter-bandwidth model read
        ("_obs.serving_adapter_slots(", 1),
        ("_obs.serving_adapter_load(", 1),
        ("_obs.serving_adapter_demoted(", 1),
        ("_obs.serving_adapter_fallback(", 1),
        # fault-injection sites: fresh load + host-store promotion —
        # both fire BEFORE any install-side mutation
        ('fault_point("adapter_load")', 1),
        ('fault_point("adapter_promote")', 1),
    ],
    "paddle_tpu/serving/host_tier.py": [
        # hierarchical KV tier (ISSUE 10): both halves of the
        # swap pair (bytes/pages + transfer latency — the
        # swap-vs-replay crossover model's inputs), the replay
        # fallback counter (the honest cost of bounding host RAM),
        # the host-pool occupancy gauges, and the demote/promote
        # counters that make the prefix tier's hit economy visible
        ("_obs.serving_swap_out(", 1),
        ("_obs.serving_swap_in(", 1),
        ("_obs.serving_swap_fallback(", 1),
        ("_obs.serving_host_pool(", 1),
        ("_obs.serving_prefix_demoted(", 1),
        ("_obs.serving_prefix_promoted(", 1),
        # fault-injection sites: swap-out BEFORE the gather, swap-in
        # BEFORE the allocation — both commit nothing when they fire
        ('fault_point("swap_out")', 1),
        ('fault_point("swap_in")', 1),
        # disk-bound pruning (ISSUE 15 satellite): the pruned-files/
        # bytes pair next to the corrupt-unlink counter
        ("_obs.serving_host_disk_pruned(", 1),
        # payload integrity (ISSUE 13): detection/quarantine/replay
        # events on the swap and promote paths + the bounded-retry
        # counter — the serving_integrity_* family the integrity gate
        # audits (detected == quarantined + replayed arithmetic)
        ("_obs.serving_integrity(", 4),
        ("_obs.serving_integrity_retry(", 1),
        ('tamper_point("swap_in")', 1),
    ],
    "paddle_tpu/serving/cluster.py": [
        # disaggregated cluster (ISSUE 9): both halves of the
        # prefill→decode handoff pair (bytes/pages moved + latency —
        # the PERF_NOTES cost model's inputs), the failover/rehome
        # counter (zero-lost-requests is only provable if rehomes are
        # visible) and the per-replica load gauges the registry-side
        # signal bus publishes each step
        ("_obs.serving_handoff_export(", 1),
        ("_obs.serving_handoff_import(", 1),
        ("_obs.serving_router_failover(", 1),
        ("_obs.serving_router_replica(", 1),
        # overload hardening (ISSUE 13): the autoscaler's event
        # counter + gauges on BOTH scale directions, the handoff
        # integrity events (a corrupt payload detected before install)
        # and the bounded-retry counter, plus the three cluster-plane
        # fault sites (export/import halves of the handoff and the
        # autoscale control tick — also enforced by check_fault_sites)
        ("_obs.serving_autoscale(", 2),
        ("_obs.serving_integrity(", 2),
        ("_obs.serving_integrity_retry(", 1),
        ('fault_point("handoff_export")', 1),
        ('fault_point("handoff_import")', 1),
        ('fault_point("autoscale_tick")', 1),
        # request tracing (ISSUE 16): router-lane minting at submit,
        # both halves of the handoff span pair (the cross-replica
        # stitch), and the structured-rejection finishes — dropping
        # one breaks the one-trace-per-request contract
        ("_obs.serving_trace_submit(", 1),
        ("_obs.serving_trace_span(", 2),
        ("_obs.serving_trace_finish(", 3),
    ],
    "paddle_tpu/serving/rpc.py": [
        # multi-process control plane (ISSUE 19): the per-call
        # latency/bytes pair on the client side + the served-side
        # decode/dispatch/encode latency, the bounded-retry counter,
        # the timeout counter and the corrupt-frame counter (client
        # CRC/torn detection AND the server's two inbound-frame
        # rejections) — the serving_rpc_* family the
        # decode_multiproc_overhead bench rider reads
        ("_obs.serving_rpc_call(", 1),
        ("_obs.serving_rpc_served(", 1),
        ("_obs.serving_rpc_retry(", 1),
        ("_obs.serving_rpc_timeout(", 1),
        ("_obs.serving_rpc_corrupt(", 4),
        # fault-injection sites: immediately BEFORE the frame send and
        # immediately AFTER the reply recv — both inside the bounded
        # retry loop, so an injected drop exercises the idempotent
        # retry + server dedupe path end to end
        ('fault_point("rpc_send")', 1),
        ('fault_point("rpc_recv")', 1),
    ],
    "paddle_tpu/serving/fabric.py": [
        # shared KV fabric (ISSUE 19): demote (put) latency/bytes,
        # promote (get) latency/bytes split by hit/miss, and the
        # quarantine counter on all three corruption seams — the
        # server's inbound CRC gate, the client's post-fetch verify
        # and the explicit peer-initiated quarantine RPC
        ("_obs.serving_fabric_demote(", 1),
        ("_obs.serving_fabric_promote(", 4),
        ("_obs.serving_fabric_quarantine(", 3),
        # fault sites: put BEFORE the demote RPC, get BEFORE the
        # promote RPC — neither commits anything when it fires
        ('fault_point("fabric_put")', 1),
        ('fault_point("fabric_get")', 1),
    ],
    "paddle_tpu/serving/node.py": [
        # replica worker (ISSUE 19): trace lanes must re-open node-side
        # on BOTH ingress edges (fresh dispatch submit and the decode
        # half of a cross-process handoff adopt) or the stitched trace
        # the controller folds together loses every worker-side span
        ("_obs.serving_trace_submit(", 2),
    ],
    "paddle_tpu/serving/router.py": [
        # cluster router (ISSUE 9): per-dispatch replica + affinity
        # hit/miss counters (the live prefix-affinity hit rate), the
        # shed-work retry counter and the rate-limit rejection counter
        ("_obs.serving_router_dispatch(", 1),
        ("_obs.serving_router_retry(", 1),
        ("_obs.serving_router_ratelimited(", 1),
        # ISSUE 13: the SLO-guarded admission rejection counter
        # (deadline-infeasible at the door) and the retry-budget
        # exhaustion counter (counted separately from first-try
        # rejection — the satellite's whole point)
        ("_obs.serving_slo_rejected(", 1),
        ("_obs.serving_router_retry_exhausted(", 1),
    ],
    "paddle_tpu/models/generate.py": [
        ("_obs.generate_begin()", 1),
        ('_obs.generate_phase("prefill"', 1),
        ('_obs.generate_phase("decode"', 1),
        # tensor-parallel serving (ISSUE 7): every traced all-gather in
        # the tp decode/prefill/verify programs counts its calls +
        # per-shard payload bytes (once per compile, like hooks.
        # collective) — dropping it blinds the tp collective counters
        ("_obs.serving_tp_allgather(", 1),
        # fused serving kernels (ISSUE 11): trace-time dispatch +
        # bytes-saved counters on BOTH fused branches (the decode
        # rope+attn fusion and the chunk/verify flash fusion) —
        # dropping one silently un-counts every launch of that kernel
        ("_obs.serving_fused_dispatch(", 2),
        # multi-LoRA serving (ISSUE 14): the trace-time adapter factor
        # gather counter — the per-step adapter bytes every compiled
        # adapter-augmented program bills (the rank-r bytes/token
        # model's live input; the serving_tp_allgather contract)
        ("_obs.serving_adapter_gather(", 1),
        # expert-parallel MoE decode (ISSUE 17): the trace-time
        # all-to-all dispatch counter at the EP branch of _moe_ffn —
        # calls, per-shard payload bytes and the routed-tokens
        # histogram (the serving_tp_allgather contract)
        ("_obs.serving_moe_dispatch(", 1),
    ],
    "paddle_tpu/io/dataloader.py": [
        ("_obs.dataloader_next(", 2),         # single-process + prefetch
        ("_obs.active()", 2),
    ],
    "paddle_tpu/distributed/collective.py": [
        ("_obs.collective(", 12),             # one per collective entry
        ('_obs.collective("all_reduce"', 1),
        ('_obs.collective("all_gather"', 1),
        ('_obs.collective("send_recv"', 1),
    ],
    "paddle_tpu/distributed/watchdog.py": [
        ("_obs.watchdog_tick(", 1),
        ("_obs.watchdog_fired(", 1),
    ],
    "paddle_tpu/profiler/utils.py": [
        ('RecordEvent("Optimizer.step"', 1),
    ],
    "bench.py": [
        ("phase_summary()", 1),
        ('"phases"', 1),
    ],
}


#: modules allowed to host fault-injection call sites (the serving hot
#: path) — the site-coverage rule greps these
_FAULT_SITE_MODULES = (
    "paddle_tpu/serving/paged_cache.py",
    "paddle_tpu/serving/scheduler.py",
    "paddle_tpu/serving/host_tier.py",
    "paddle_tpu/serving/cluster.py",
    "paddle_tpu/serving/adapters.py",
    "paddle_tpu/serving/wal.py",
    "paddle_tpu/serving/rpc.py",
    "paddle_tpu/serving/fabric.py",
    "paddle_tpu/inference/predictor.py",
)


def check_fault_sites(root: str) -> list:
    """ISSUE 8 rule: every FaultInjector site name declared in
    ``serving/resilience.py``'s ``SITES`` tuple must have a matching
    ``fault_point("<site>")`` call threaded through a hot-path module —
    a declared-but-unthreaded site would silently produce NO
    ``serving_fault_*{site=...}`` counter label, and chaos coverage of
    that site would be a no-op that still claims the site was
    exercised."""
    import re
    problems = []
    res_path = os.path.join(root, "paddle_tpu/serving/resilience.py")
    if not os.path.exists(res_path):
        return [f"paddle_tpu/serving/resilience.py: file missing"]
    with open(res_path, encoding="utf-8") as f:
        src = f.read()
    # SITES is composed from the engine-plane and cluster-plane
    # tuples (ISSUE 13) — collect the declared names from both
    sites = []
    for name in ("ENGINE_SITES", "CLUSTER_SITES"):
        m = re.search(rf"^{name}\s*=\s*\(([^)]*)\)", src, re.M)
        if not m:
            return [f"paddle_tpu/serving/resilience.py: {name} "
                    f"tuple missing"]
        sites += re.findall(r"\"([a-z_]+)\"", m.group(1))
    if not sites:
        return ["paddle_tpu/serving/resilience.py: SITES tuples empty"]
    hot = ""
    for rel in _FAULT_SITE_MODULES:
        path = os.path.join(root, rel)
        if os.path.exists(path):
            with open(path, encoding="utf-8") as f:
                hot += f.read()
    for site in sites:
        if f'fault_point("{site}")' not in hot:
            problems.append(
                f"paddle_tpu/serving/resilience.py: SITES declares "
                f"{site!r} but no hot-path module calls "
                f"fault_point(\"{site}\") — the serving_fault_* "
                f"counters would never carry that site label")
    return problems


#: the sync-point discipline of the overlapped runtime (ISSUE 12):
#: module -> function names whose bodies must stay FREE of device→host
#: sync idioms (single-argument ``np.asarray(...)`` fetches and
#: ``block_until_ready``). None = the whole module. The scheduler's
#: host plane and the engine's DISPATCH-path functions plan and launch
#: only — every fetch of a step result belongs in the commit helpers
#: (_decode_commit / _spec_commit / _commit_chunk), or the overlap
#: pipeline silently degrades back to a synchronous chain.
_SYNC_FREE = {
    "paddle_tpu/serving/scheduler.py": None,
    # _tree_dispatch launches the one-forward tree verify and must not
    # fetch its logits or KV rows (both ride the InFlightStep to
    # _tree_commit); _propose_model_drafts is deliberately NOT listed —
    # the draft loop is sequential by construction (each draft token
    # feeds the next step), so its per-step logits fetch is the design,
    # not a regression
    "paddle_tpu/inference/predictor.py": (
        "decode_dispatch", "spec_dispatch", "prefill_dispatch",
        "ready_mask", "propose_drafts", "spec_plan_widths",
        "_tree_dispatch"),
    # the tracing layer (ISSUE 16) runs INSIDE the hot path on every
    # span close — it must never fetch a device value or fence; its
    # zero-device-syncs contract is what lets call sites fire between
    # dispatch and commit
    "paddle_tpu/observability/tracing.py": None,
    # the RPC layer (ISSUE 19) frames host bytes only — it must never
    # import jax or fetch a device value; KV payloads reach it already
    # exported as host numpy views, and keeping it device-blind is
    # what lets the fabric server run as a jax-free process
    "paddle_tpu/serving/rpc.py": None,
}

#: device-sync idioms: a bare one-argument np.asarray (dtype-annotated
#: conversions of host arrays pass — they never touch device values on
#: these paths) and any block_until_ready
_SYNC_RE = (r"(?<!j)np\.asarray\([^,()]*(\([^()]*\))?[^,()]*\)(?!\s*,)",
            r"block_until_ready")


def _function_bodies(src: str, names) -> str:
    """Concatenate the bodies of the named top-level-in-class defs
    (selected by indentation: a body line is any line more indented
    than its ``def``)."""
    import re
    out = []
    lines = src.splitlines()
    for name in names:
        for i, line in enumerate(lines):
            m = re.match(rf"(\s*)def {re.escape(name)}\(", line)
            if not m:
                continue
            indent = len(m.group(1))
            j = i + 1
            while j < len(lines):
                ln = lines[j]
                if ln.strip() and (len(ln) - len(ln.lstrip())) <= indent:
                    break
                out.append(ln)
                j += 1
    return "\n".join(out)


def check_sync_points(root: str) -> list:
    """ISSUE 12 rule: no ``np.asarray`` / ``block_until_ready`` on
    step results outside the commit helpers in the scheduler/predictor
    hot paths. The textual heuristic flags single-argument
    ``np.asarray(x)`` (the device-fetch idiom) and any
    ``block_until_ready`` inside the :data:`_SYNC_FREE` scopes —
    dtype-annotated conversions (``np.asarray(x, np.int32)``) are
    host-side and pass."""
    import re
    problems = []
    for rel, names in _SYNC_FREE.items():
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: file missing")
            continue
        with open(path, encoding="utf-8") as f:
            src = f.read()
        scope = src if names is None else _function_bodies(src, names)
        where = ("module" if names is None
                 else "dispatch-path functions " + "/".join(names))
        for pat in _SYNC_RE:
            for m in re.finditer(pat, scope):
                problems.append(
                    f"{rel}: device-sync idiom {m.group(0)!r} in the "
                    f"{where} — step results must be fetched only in "
                    f"the commit helpers (the overlapped runtime's "
                    f"single-fence contract, ISSUE 12)")
    return problems


def check(root: str) -> list:
    """Returns a list of human-readable violation strings (empty = ok)."""
    problems = check_fault_sites(root) + check_sync_points(root)
    for rel, rules in REQUIRED.items():
        path = os.path.join(root, rel)
        if not os.path.exists(path):
            problems.append(f"{rel}: file missing")
            continue
        with open(path, encoding="utf-8") as f:
            src = f.read()
        for needle, min_count in rules:
            n = src.count(needle)
            if n < min_count:
                problems.append(
                    f"{rel}: expected >= {min_count} occurrence(s) of "
                    f"{needle!r}, found {n} — a telemetry hook was "
                    f"dropped (see paddle_tpu/observability/hooks.py)")
    return problems


def main() -> int:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    problems = check(root)
    if problems:
        for p in problems:
            print(f"check_instrumentation: {p}", file=sys.stderr)
        return 1
    print(f"check_instrumentation: {len(REQUIRED)} hot-path modules ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
