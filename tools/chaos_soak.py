#!/usr/bin/env python
"""Chaos soak for the fault-tolerant serving supervisor (ISSUE 8).

Runs a SEEDED mixed workload — chunked prefill, plain decode,
speculative verify, priority preemption — through an
:class:`~paddle_tpu.serving.EngineSupervisor` while a deterministic
:class:`~paddle_tpu.serving.FaultInjector` fires at least ``--faults``
faults across EVERY hot-path site (allocator alloc/free, decode /
prefill-chunk / verify execution, device→host transfer, scheduler
tick, host-tier swap out/in, the overlapped runtime's dispatch/commit
seams — ISSUE 12 — the adapter plane's load/promote sites with
multi-LoRA traffic live — ISSUE 14 — and the draft-model tree
speculation plane's propose/verify sites via a second supervised
engine — ISSUE 20; raise + stall + corrupt modes),
then asserts the invariants that make recovery trustworthy:

- **zero lost requests** — every submitted request finishes with a
  structured reason (eos / max_len / rejected_overload when the
  degraded ladder sheds LOW traffic);
- **zero duplicated requests** — every completed request's token
  stream is EXACTLY the uninterrupted reference (bit-identical; a
  double-committed or replayed-twice token would show here);
- **balanced allocator** — the final engine drains to zero pages in
  use with ``allocs_total == frees_total`` once the prefix trie drops
  its references;
- **every fault visible** — the ``serving_fault_injected_total``
  counters account for every injector firing, per site.

Usage (seeded, CPU-friendly; also wired into tier-1 through
tests/test_resilience.py):

    JAX_PLATFORMS=cpu python tools/chaos_soak.py --seed 0 --faults 50
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

import numpy as np

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


class SoakError(AssertionError):
    """A soak invariant failed (the tool's single failure type)."""


def _speculator(spec_k):
    """Deterministic always-draft speculator: proposes the last history
    token repeated — verify runs every step (exercising the
    verify/transfer sites) and drafts are accepted exactly when the
    model truly repeats, so greedy output stays bit-identical by the
    standard acceptance rule."""
    from paddle_tpu.serving import Speculator

    class _RepeatLast(Speculator):
        def propose(self, slot, rid, history, cap=None):
            k = self.max_k if cap is None else min(self.max_k, int(cap))
            if k <= 0 or len(history) == 0:
                return np.zeros((0,), np.int32)
            return np.full((k,), history[-1], np.int32)

    return _RepeatLast(spec_k)


def run_soak(seed: int = 0, faults: int = 50, requests: int = 24,
             max_steps: int = 20000, stall_faults: int = 2,
             tp: int = None, dp: int = 1) -> dict:
    """One seeded soak; returns the report dict (raises
    :class:`SoakError` on any invariant violation).

    ``tp``/``dp`` (ISSUE 17) put the SOAKED engine on a
    ``serving_mesh(tp, dp)`` while the per-request references stay
    single-chip — the parity gate then doubles as the 2-D-mesh
    identity gate under fault fire: every recovery rebuild, swap
    round-trip and journal replay must reproduce the single-chip
    token streams exactly."""
    import tempfile

    import jax
    from paddle_tpu import observability as obs
    from paddle_tpu.models import llama
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.serving import (AdapterPool, AdapterRegistry,
                                    EngineDead, EngineSupervisor,
                                    FaultInjector, HostPageStore,
                                    InjectedFault, Priority, init_lora)
    from paddle_tpu.serving.resilience import ENGINE_SITES as SITES

    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=64)
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = None
    if tp:
        from paddle_tpu.distributed.mesh import serving_mesh
        if len(jax.devices()) < tp * dp:
            raise RuntimeError(
                f"soak tp={tp} x dp={dp} needs {tp * dp} devices")
        mesh = serving_mesh(tp, dp)
    rs = np.random.RandomState(seed)
    spec_k = 2
    # adapter plane (ISSUE 14): three LoRA variants over a TWO-slot
    # pool with a host store below it — cycling adapter ids through
    # the workload forces loads, LRU evictions (demote) and
    # promotions, so the adapter_load / adapter_promote fault sites
    # get organic visits under the same zero-lost/zero-duplicated
    # gate. One registry describes the population; the supervisor's
    # pool is SHARED across recovery rebuilds (the host-tier pattern:
    # pool state commits at admission, never mid-step) while the
    # reference engine gets its own pool so reference runs never
    # touch the soaked pool's residency.
    registry = AdapterRegistry(cfg)
    for aid in (1, 2, 3):
        registry.register(aid, init_lora(cfg, 4, seed=100 + aid))

    def make_pool(reference=False):
        # the pool's B factors shard with the weights, so the soaked
        # pool is built on the soak mesh (if any) and the reference
        # pool stays single-chip like its engine
        return AdapterPool(cfg, slots=2, rank=4, registry=registry,
                           store=HostPageStore(page_size=8),
                           mesh=None if reference else mesh)

    soak_pool = make_pool()

    def factory(pool=None, reference=False):
        # host tier ON (ISSUE 10): preemptions swap out / resumes swap
        # in, so the soak's fault stream also exercises the swap_out /
        # swap_in sites under the same zero-lost/zero-duplicated gate.
        # overlap ON (ISSUE 12): the supervisor's scheduler runs the
        # double-buffered pipeline — faults at the new dispatch/commit
        # seams (and between them) must recover token-identically via
        # journal replay, and preemption swap-outs go through the
        # async DMA + commit-fence path. The per-request references
        # run through engine.generate(), which is synchronous
        # regardless of the knob — so the soak's parity gate is ALSO
        # the overlap-vs-sync identity gate, under fault fire.
        # On a 2-D mesh (ISSUE 17) the soaked engine scales its batch
        # to 3 rows PER dp shard while references stay single-chip at
        # max_batch=3: per-shard geometry matches the reference's, so
        # the parity check below is exactly the 2-D identity gate.
        mb = 3 if (reference or mesh is None) else 3 * dp
        return ContinuousBatchingEngine(
            params, cfg, max_batch=mb, page_size=8, max_len=48,
            prefill_chunk=8, spec_k=spec_k,
            speculator=_speculator(spec_k), host_tier=True,
            overlap=True, mesh=None if reference else mesh,
            adapters=pool if pool is not None else soak_pool)

    # mixed workload: long prompts (multi-chunk prefill), short ones,
    # repetitive motifs (accepted drafts), three priority classes
    # (HIGH admissions preempt LOW runners); every request cycles
    # through adapter ids 0..3 (0 = base) so the 2-slot pool churns
    jobs = []
    for i in range(requests):
        # the motif (draftable) job leads: spec verify only runs at
        # degraded level 0, and the armed-fault ramp starts escalating
        # the ladder within a few admissions — the first verify must
        # happen before that (ISSUE 15 widened the armed set, which
        # pushed the old ordering's first verify past the first rung)
        kind = (i + 2) % 4
        aid = i % 4                                # adapter id 0..3
        if kind == 0:
            n = int(rs.randint(18, 30))            # chunked prefill
        elif kind == 1:
            n = int(rs.randint(3, 8))              # short
        elif kind == 2:
            motif = rs.randint(3, cfg.vocab_size, (3,))
            jobs.append((np.tile(motif, 5).astype(np.int32)[:14],
                         int(rs.randint(4, 7)),
                         Priority(int(rs.randint(0, 3))), aid))
            continue
        else:
            n = int(rs.randint(8, 16))
        jobs.append((rs.randint(3, cfg.vocab_size, (n,)).astype(np.int32),
                     int(rs.randint(4, 7)),
                     Priority(int(rs.randint(0, 3))), aid))

    # uninterrupted references, one engine run per request (per-row
    # greedy decode is independent of batch composition — the PR 2-5
    # parity gates — so per-request references are exact)
    ref_engine = factory(pool=make_pool(reference=True),
                         reference=True)

    def ref_run(p, m, aid=0):
        r = ref_engine.submit(p, max_new_tokens=m, adapter_id=aid)
        ref_engine.run()
        return np.asarray(r.output)

    refs = [ref_run(p, m, aid) for p, m, _, aid in jobs]

    was = obs.metrics_enabled()
    obs.REGISTRY.clear()
    obs.enable()
    t_start = time.perf_counter()
    try:
        inj = FaultInjector(
            seed=seed, rate=0.02, modes=("raise", "corrupt"),
            max_faults=faults, stall_s=2.5)
        # guarantee coverage: arm one fault at EVERY site up front
        # (the rate-based stream fills in the rest), plus a couple of
        # watchdog stalls. The swap sites are visited far less often
        # than the per-step sites (once per preemption/resume, not per
        # step), so their armed shots sit on early calls: the FIRST
        # swap-out succeeds (a payload must exist for any swap-in to
        # run at all — and a recovery rebuilds a fresh engine with
        # every slot free, so a faulted swap-out is not re-attempted
        # until the next drill round preempts again), the second
        # faults; the first swap-in faults and its retry proves the
        # payload survived the recovery.
        for i, site in enumerate(SITES):
            if site == "swap_out":
                inj.arm(site, "raise", nth=2)
            elif site == "swap_in":
                inj.arm(site, "raise", nth=1)
            elif site == "tree_verify":
                # visited only by the ISSUE 20 tree interlude below:
                # the FIRST one-forward tree verify eats the shot —
                # it fires BEFORE the verify launches, so nothing
                # committed and recovery rebuilds the draft pool cold
                inj.arm(site, "raise", nth=1)
            elif site == "draft_propose":
                # the first propose must succeed (the interlude needs
                # at least one full propose->verify->commit round and
                # a rejection cascade against a LIVE draft pool before
                # a fault tears it down); the recover_after=2 tree
                # supervisor climbs back to healthy fast enough for
                # the second propose to eat the shot
                inj.arm(site, "raise", nth=2)
            elif site == "adapter_load":
                # fires once per FRESH registry load (a handful per
                # soak, not per step): the first load must succeed so
                # an eviction/demotion can ever happen, the second
                # eats the shot — the re-admission after recovery
                # retries against an intact registry
                inj.arm(site, "raise", nth=2)
            elif site == "adapter_promote":
                # fires once per host-store promotion (needs a prior
                # LRU demotion): the first promotion faults, and the
                # retried admission proves the demoted payload
                # survived the fault un-installed
                inj.arm(site, "raise", nth=1)
            elif site == "verify_step":
                # spec verify only runs at degraded level 0 — the
                # first recovery shelves it (no_spec) and every armed
                # fault elsewhere costs a recovery, so the verify shot
                # must land on the FIRST call or the site may never be
                # visited again before the soak drains (the ISSUE 15
                # wal sites joined the rate stream, which reshuffled
                # the seeded recovery timing that nth=2 relied on)
                inj.arm(site, "raise", nth=1)
            elif site == "checkpoint_write":
                # one visit per checkpoint_every steps — a deep nth
                # may never be reached in a short soak; the first
                # checkpoint is expendable (it commits nothing when it
                # faults, and the next period retries)
                inj.arm(site, "raise", nth=1)
            else:
                inj.arm(site, "raise", nth=3 + 2 * i)
        for i in range(stall_faults):
            inj.arm("transfer", "stall", nth=30 + 40 * i)
        # durable journal ON (ISSUE 15): per-step delta cadence
        # (group_interval_s=0) + a small checkpoint period so the
        # wal_append / wal_fsync / checkpoint_write sites get organic
        # per-step visits under the same zero-lost/duplicated gate
        sup = EngineSupervisor(
            factory, watchdog_s=2.0, backoff_s=0.0,
            sleep=lambda s: None, circuit_threshold=10,
            recover_after=8,
            wal_dir=tempfile.mkdtemp(prefix="chaos_wal_"),
            checkpoint_every=16, wal_kw=dict(group_interval_s=0.0))

        def submit(p, m, prio=Priority.NORMAL, aid=0):
            # a fault at the write-ahead append rejects the submission
            # BEFORE the ack — the client's move is a plain retry, and
            # nothing was half-accepted (the append rolls back)
            while True:
                try:
                    return sup.submit(p, max_new_tokens=m,
                                      priority=prio, adapter_id=aid)
                except InjectedFault:
                    continue
        reqs = []
        steps = 0
        with inj:
            # TRICKLE the submissions (two steps between arrivals)
            # instead of batching them up front: strictly-by-class
            # admission would otherwise drain every HIGH before any
            # LOW ever holds a slot, and the preemption path — and
            # with it the host tier's swap_out/swap_in sites
            # (ISSUE 10) — would never execute. Arrival dynamics are
            # what make HIGH-preempts-running-LOW happen.
            for p, m, prio, aid in jobs:
                reqs.append(submit(p, m, prio=prio, aid=aid))
                for _ in range(2):
                    try:
                        sup.step()
                    except EngineDead:
                        raise SoakError(
                            "circuit breaker opened mid-soak — raise "
                            "circuit_threshold or lower the fault rate")
                    steps += 1
            while True:
                try:
                    if not sup.step():
                        break
                except EngineDead:
                    raise SoakError(
                        "circuit breaker opened mid-soak — raise "
                        "circuit_threshold or lower the fault rate")
                steps += 1
                if steps >= max_steps:
                    raise SoakError(f"soak did not drain within "
                                    f"{max_steps} steps")
            # deterministic SWAP DRILL (ISSUE 10): two rounds of
            # fill-slots-then-HIGH-preempts, so the swap_out/swap_in
            # sites get guaranteed visits (and their armed shots
            # guaranteed firings) even at small --requests where the
            # organic arrival mix may preempt only once. The fillers
            # are NORMAL class — the degraded ladder may be shedding
            # LOW by now, and a shed filler never occupies the slot a
            # preemption needs. References for these requests are
            # computed after the injector uninstalls, like the
            # top-ups'.
            # ROUND COUNT IS ADAPTIVE (ISSUE 13): a round's HIGH can
            # land just as a filler retires (admitting into the freed
            # slot, no preemption), and the bounded swap-in retry
            # absorbed a recovery that used to reshape the dynamics —
            # so loop until the swap_out site has genuinely been
            # visited twice (first call succeeds, second eats the
            # armed shot) instead of assuming two rounds suffice
            topup_jobs = []
            # decode-heavy fillers on a dp-widened batch (ISSUE 17):
            # chunked prefill admits ~one filler per step (the chunk
            # budget), so the LAST slot starts decoding ~max_batch
            # steps after the first — the first filler must still be
            # decoding then (even at full spec acceptance, 3
            # tokens/step) or the all-slots-swappable window the HIGH
            # preemption needs never opens
            fill_new = 6 if mesh is None else 6 + 9 * dp
            drill_rounds = 0
            while inj.calls["swap_out"] < 2 and drill_rounds < 8:
                drill_rounds += 1
                lows = []
                # fill EVERY slot with decode-phase NORMAL work, topping
                # up as earlier fillers finish (or recoveries churn the
                # slots): the HIGH below must find no free slot and only
                # swappable victims, or the admission would not preempt
                # and the swap sites would go unvisited — the organic
                # phase's preemption count depends on the seeded fault
                # sequence, which shifts whenever SITES grows (ISSUE 12
                # added dispatch/commit), so the drill must not rely on it
                while True:
                    eng = sup.engine       # recoveries swap the engine
                    running = eng.running_requests()
                    if (len(running) == eng.max_batch
                            and all(eng.swap_candidate(r)
                                    for r in running)):
                        break
                    # top up the FULL deficit, not one per step: at
                    # dp-widened max_batch a filler's lifetime is
                    # fewer steps than there are slots, so
                    # one-per-step arrivals can never have every slot
                    # occupied at once
                    while sum(1 for r in lows
                              if not r.done) < eng.max_batch:
                        p = rs.randint(3, cfg.vocab_size, (6,)).astype(
                            np.int32)
                        lows.append(submit(p, fill_new))
                        reqs.append(lows[-1])
                        topup_jobs.append((p, fill_new))
                    try:
                        sup.step()
                    except EngineDead:
                        raise SoakError("circuit opened in swap drill")
                    steps += 1
                    if steps >= max_steps:
                        raise SoakError("swap drill did not settle")
                p = rs.randint(3, cfg.vocab_size, (4,)).astype(np.int32)
                reqs.append(submit(p, 2, prio=Priority.HIGH))
                topup_jobs.append((p, 2))
                while True:
                    try:
                        if not sup.step():
                            break
                    except EngineDead:
                        raise SoakError("circuit opened in swap drill")
                    steps += 1
                    if steps >= max_steps:
                        raise SoakError("swap drill did not drain")
            # ---- draft-model TREE speculation interlude (ISSUE 20):
            # a SECOND supervised engine on the same injector — the
            # truncated-layer draft model proposes token trees, one
            # forward verifies them, and the armed draft_propose /
            # tree_verify shots (both fire BEFORE any commit) land
            # mid-traffic. recover_after=2 so the no_spec rung the
            # first fault buys climbs off fast enough for the second
            # armed site to be visited again before the drain.
            # References are computed after the injector uninstalls,
            # on the plain reference engine: tree speculation is
            # token-identical to plain decode, so the standing parity
            # gate doubles as the tree-identity gate under fault fire.
            def tree_factory():
                return ContinuousBatchingEngine(
                    params, cfg, max_batch=3, page_size=8, max_len=48,
                    prefill_chunk=8, draft_layers=1, spec_tree=(2, 2),
                    overlap=True)

            tsup = EngineSupervisor(
                tree_factory, watchdog_s=2.0, backoff_s=0.0,
                sleep=lambda s: None, circuit_threshold=10,
                recover_after=2,
                wal_dir=tempfile.mkdtemp(prefix="chaos_tree_wal_"),
                checkpoint_every=16, wal_kw=dict(group_interval_s=0.0))
            tree_jobs, tree_reqs = [], []
            for i in range(8):
                if i % 2:
                    motif = rs.randint(3, cfg.vocab_size, (3,))
                    p = np.tile(motif, 5).astype(np.int32)[:12]
                else:
                    p = rs.randint(3, cfg.vocab_size, (int(
                        rs.randint(4, 14)),)).astype(np.int32)
                m = int(rs.randint(4, 7))
                while True:
                    try:
                        tree_reqs.append(tsup.submit(
                            p, max_new_tokens=m))
                        break
                    except InjectedFault:
                        continue
                tree_jobs.append((p, m))
                for _ in range(2):
                    try:
                        tsup.step()
                    except EngineDead:
                        raise SoakError(
                            "circuit opened in tree interlude")
                    steps += 1
            while True:
                try:
                    if not tsup.step():
                        break
                except EngineDead:
                    raise SoakError("circuit opened in tree interlude")
                steps += 1
                if steps >= max_steps:
                    raise SoakError("tree interlude did not drain")
            # keep injecting until the fault budget is spent: top up
            # with fresh NORMAL traffic so every site stays hot (the
            # top-ups' uninterrupted references are computed AFTER the
            # injector uninstalls — a faulted reference run would gate
            # parity against a poisoned oracle)
            topup = 0
            while inj.fired_total < faults:
                p = rs.randint(3, cfg.vocab_size,
                               (int(rs.randint(3, 20)),)).astype(np.int32)
                m = int(rs.randint(3, 6))
                r = submit(p, m)
                jobs.append((p, m, Priority.NORMAL, 0))
                reqs.append(r)
                topup_jobs.append((p, m))
                topup += 1
                while True:
                    try:
                        if not sup.step():
                            break
                    except EngineDead:
                        raise SoakError("circuit breaker opened during "
                                        "fault-budget top-up")
                    steps += 1
                    if steps >= max_steps:
                        raise SoakError(f"top-up did not drain within "
                                        f"{max_steps} steps")
                if topup > 8 * faults:
                    raise SoakError(
                        f"fault budget not spent after {topup} top-up "
                        f"requests ({inj.fired_total}/{faults}) — the "
                        f"rate is too low for the workload")
        for p, m in topup_jobs:
            # the ONE reference engine serves every reference run (its
            # compiled programs amortize across the whole soak)
            refs.append(ref_run(p, m))
        tree_refs = [ref_run(p, m) for p, m in tree_jobs]
        snap = obs.REGISTRY.to_json()
    finally:
        obs.REGISTRY.clear()
        if not was:
            obs.disable()

    # ---- invariants ----
    lost = [r.rid for r in reqs if not r.done or r.finish_reason is None]
    if lost:
        raise SoakError(f"lost requests (not done after drain): {lost}")
    shed = [r for r in reqs if r.finish_reason == "rejected_overload"]
    ok_reasons = {"eos", "max_len", "rejected_overload"}
    bad = [(r.rid, r.finish_reason) for r in reqs
           if r.finish_reason not in ok_reasons]
    if bad:
        raise SoakError(f"unstructured finish reasons: {bad}")
    mismatched = []
    for r, ref in zip(reqs, refs):
        if r.finish_reason == "rejected_overload":
            if r.tokens:
                mismatched.append((r.rid, "shed request has tokens"))
            continue
        if not np.array_equal(r.output, ref):
            mismatched.append((r.rid, "token stream != uninterrupted"))
    if mismatched:
        raise SoakError(
            f"duplicated/diverged token streams: {mismatched}")
    alloc = sup.engine.cache.allocator
    if sup.engine.cache.prefix is not None:
        sup.engine.cache.prefix.drop_all(alloc)
    astats = alloc.stats()
    if astats["num_used"] != 0 or \
            astats["allocs_total"] != astats["frees_total"]:
        raise SoakError(f"allocator unbalanced after drain: {astats}")
    # ---- ISSUE 20 tree-interlude invariants: zero lost, streams
    # token-identical to plain decode, and BOTH pools balanced — the
    # draft pool drained through admits, rejection cascades, faults
    # and cold recovery rebuilds, so a leaked draft page shows here
    tlost = [r.rid for r in tree_reqs
             if not r.done or r.finish_reason not in ("eos", "max_len")]
    if tlost:
        raise SoakError(f"tree interlude lost requests: {tlost}")
    tmism = [r.rid for r, ref in zip(tree_reqs, tree_refs)
             if not np.array_equal(r.output, ref)]
    if tmism:
        raise SoakError(f"tree-speculated streams diverged from plain "
                        f"decode under fault fire: {tmism}")
    talloc = tsup.engine.cache.allocator
    if tsup.engine.cache.prefix is not None:
        tsup.engine.cache.prefix.drop_all(talloc)
    tstats = talloc.stats()
    dstats = tsup.engine.draft_cache.allocator.stats()
    if tstats["num_used"] != 0 or dstats["num_used"] != 0 or \
            dstats["allocs_total"] != dstats["frees_total"]:
        raise SoakError(f"tree engine pools unbalanced after drain: "
                        f"main={tstats} draft={dstats}")
    if inj.fired_total < faults:
        raise SoakError(f"only {inj.fired_total}/{faults} faults fired")
    missing = [s for s in SITES if not inj.fired.get(s)]
    if missing:
        raise SoakError(f"sites never faulted: {missing}")
    counted = sum(
        snap.get("serving_fault_injected_total", {})
        .get("values", {}).values())
    if counted != inj.fired_total:
        raise SoakError(
            f"metrics saw {counted} injected faults, injector fired "
            f"{inj.fired_total} — a fault escaped the counters")
    labeled_sites = {
        k.split("site=")[1].split(",")[0]
        for k in snap["serving_fault_injected_total"]["values"]}
    if set(SITES) - labeled_sites:
        raise SoakError(f"sites missing from serving_fault_* labels: "
                        f"{sorted(set(SITES) - labeled_sites)}")

    return {
        "seed": seed,
        "requests": len(reqs),
        **({"tp": tp, "dp": dp} if mesh is not None else {}),
        "shed_rejected_overload": len(shed),
        "faults_fired": inj.fired_total,
        "faults_by_site": {s: n for s, n in inj.fired.items() if n},
        "recoveries": sup.recoveries,
        "tree_interlude": {
            "requests": len(tree_reqs),
            "recoveries": tsup.recoveries,
            "draft_propose_fired": int(inj.fired.get(
                "draft_propose", 0)),
            "tree_verify_fired": int(inj.fired.get("tree_verify", 0)),
            "draft_pool": {k: dstats[k] for k in
                           ("allocs_total", "frees_total", "num_used")},
        },
        "supervised_steps": sup.stats()["supervised_steps"],
        "final_degraded_mode": sup.degraded_mode,
        "allocator": {k: astats[k] for k in
                      ("allocs_total", "frees_total", "num_used")},
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }


def run_cluster_soak(seed: int = 0, requests: int = 18,
                     replicas: int = 3, max_steps: int = 20000) -> dict:
    """Cluster-mode soak (ISSUE 9): a multi-tenant shared-prefix
    workload through a :class:`~paddle_tpu.serving.ServingCluster`
    while a deterministic :class:`~paddle_tpu.serving.FaultInjector`
    KILLS a random replica mid-soak — ``circuit_threshold``
    consecutive armed faults at the ``sched_tick`` site blow whichever
    replica steps next straight through its circuit breaker (the same
    hot-path sites the single-engine soak exercises). Invariants:

    - **zero lost / duplicated requests cluster-wide** — every request
      finishes with a structured reason and a token stream EXACTLY
      equal to its uninterrupted single-engine reference (the dead
      replica's sessions rehome and resume token-identically);
    - **prefix-affinity recovers** — after the replica rebuilds, fresh
      same-tenant traffic produces prefix HITs again (counter-gated:
      the hit-token counter and the router's affinity-hit counter both
      advance post-rebuild);
    - **balanced allocators** — every surviving replica drains to zero
      pages in use with ``allocs_total == frees_total``.

    Wired into tier-1 via tests/test_cluster.py::TestClusterChaosSoak.
    """
    import jax
    from paddle_tpu import observability as obs
    from paddle_tpu.models import llama
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.serving import (FaultInjector, Priority,
                                    ServingCluster)

    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=64)
    params = llama.init_params(jax.random.key(0), cfg)
    rs = np.random.RandomState(seed)
    circuit = 3

    def factory():
        # host tier ON (ISSUE 10); the cluster shares ONE HostPageStore
        # across replicas (share_host_tier default), so sessions the
        # killed replica swapped out SWAP IN on the replica they rehome
        # to — the failover path exercises the cross-replica host tier.
        # overlap ON (ISSUE 12): every supervised replica runs the
        # double-buffered scheduler, so the replica kill lands with a
        # step in flight and the rehomed sessions' resumes gate the
        # overlapped cluster against the synchronous references.
        return ContinuousBatchingEngine(
            params, cfg, max_batch=2, page_size=8, max_len=48,
            prefill_chunk=8, host_tier=True, overlap=True)

    # multi-tenant workload: each tenant has its own system prompt
    # (affinity + prefix hits) plus a unique tail, three priorities
    tenants = [f"tenant{i}" for i in range(3)]
    sys_prompts = {t: rs.randint(3, cfg.vocab_size, (16,)).astype(
        np.int32) for t in tenants}

    def make_job():
        t = tenants[int(rs.randint(len(tenants)))]
        tail = rs.randint(3, cfg.vocab_size,
                          (int(rs.randint(2, 8)),)).astype(np.int32)
        return (t, np.concatenate([sys_prompts[t], tail]),
                int(rs.randint(3, 6)),
                Priority(int(rs.randint(0, 3))))

    jobs = [make_job() for _ in range(requests)]
    ref_engine = factory()
    refs = [np.asarray(ref_engine.generate([p], max_new_tokens=m)[0])
            for _, p, m, _ in jobs]

    was = obs.metrics_enabled()
    obs.REGISTRY.clear()
    obs.enable()
    t_start = time.perf_counter()
    try:
        cluster = ServingCluster(
            factory, replicas=replicas,
            supervisor_kw=dict(backoff_s=0.0, sleep=lambda s: None,
                               circuit_threshold=circuit,
                               recover_after=4))
        inj = FaultInjector(seed=seed)
        reqs = []
        with inj:
            for t, p, m, prio in jobs:
                reqs.append(cluster.submit(p, max_new_tokens=m,
                                           tenant=t, priority=prio))
            # let traffic occupy every replica, then KILL one: arm
            # circuit_threshold consecutive sched_tick faults — the
            # next replica to step burns through its whole retry
            # budget and opens its circuit (EngineDead -> failover)
            steps = 0
            for _ in range(3):
                cluster.step()
                steps += 1
            for _ in range(circuit):
                inj.arm("sched_tick", "raise", nth=1)
            failovers_before = cluster.failovers_total
            hits_before = cluster.router.affinity_hits
            while cluster.step():
                steps += 1
                if steps >= max_steps:
                    raise SoakError(f"cluster soak did not drain "
                                    f"within {max_steps} steps")
        if cluster.failovers_total <= failovers_before:
            raise SoakError("the armed fault burst did not kill a "
                            "replica — nothing failed over")
        # post-rebuild traffic: the SAME tenants return; affinity and
        # prefix hits must recover (references computed with the
        # injector uninstalled)
        hit0 = sum(obs.REGISTRY.to_json()
                   .get("serving_prefix_hit_tokens_total", {})
                   .get("values", {}).values())
        post_jobs = [make_job() for _ in range(6)]
        for t, p, m, prio in post_jobs:
            reqs.append(cluster.submit(p, max_new_tokens=m, tenant=t,
                                       priority=prio))
            jobs.append((t, p, m, prio))
        while cluster.step():
            steps += 1
            if steps >= max_steps:
                raise SoakError("post-rebuild traffic did not drain")
        for _, p, m, _ in post_jobs:
            refs.append(np.asarray(
                ref_engine.generate([p], max_new_tokens=m)[0]))
        snap = obs.REGISTRY.to_json()
    finally:
        obs.REGISTRY.clear()
        if not was:
            obs.disable()

    # ---- invariants ----
    lost = [r.rid for r in reqs if not r.done or r.finish_reason is None]
    if lost:
        raise SoakError(f"lost requests (not done after drain): {lost}")
    ok_reasons = {"eos", "max_len", "rejected_overload"}
    bad = [(r.rid, r.finish_reason) for r in reqs
           if r.finish_reason not in ok_reasons]
    if bad:
        raise SoakError(f"unstructured finish reasons: {bad}")
    mismatched = []
    for r, ref in zip(reqs, refs):
        if r.finish_reason == "rejected_overload":
            if r.tokens:
                mismatched.append((r.rid, "shed request has tokens"))
            continue
        if not np.array_equal(r.output, ref):
            mismatched.append((r.rid, "token stream != uninterrupted"))
    if mismatched:
        raise SoakError(
            f"duplicated/diverged token streams: {mismatched}")
    hit1 = sum(snap.get("serving_prefix_hit_tokens_total", {})
               .get("values", {}).values())
    if hit1 <= hit0:
        raise SoakError(
            f"prefix hit-rate did not recover after the replica "
            f"rebuild (hit tokens {hit0} -> {hit1})")
    if cluster.router.affinity_hits <= hits_before:
        raise SoakError("router affinity hits did not advance after "
                        "the failover")
    unbalanced = {}
    for i, sup in enumerate(cluster.replicas):
        alloc = sup.engine.cache.allocator
        if sup.engine.cache.prefix is not None:
            sup.engine.cache.prefix.drop_all(alloc)
        st = alloc.stats()
        if st["num_used"] != 0 or \
                st["allocs_total"] != st["frees_total"]:
            unbalanced[i] = st
    if unbalanced:
        raise SoakError(f"allocator unbalanced after drain: "
                        f"{unbalanced}")

    return {
        "seed": seed,
        "mode": "cluster",
        "replicas": replicas,
        "requests": len(reqs),
        "shed_rejected_overload": len(
            [r for r in reqs if r.finish_reason == "rejected_overload"]),
        "failovers": cluster.failovers_total,
        "handoffs": cluster.handoffs_total,
        "rehomed_sessions": int(
            sum(snap.get("serving_router_rehomed_sessions_total", {})
                .get("values", {}).values())),
        "affinity_hit_rate": round(
            cluster.router.stats()["affinity_hit_rate"], 3),
        "prefix_hit_tokens": int(hit1),
        "cluster_steps": cluster.stats()["cluster_steps"],
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }


def run_traffic_soak(seed: int = 0, duration_s: float = 3.0,
                     base_rps: float = 8.0,
                     max_steps: int = 40000) -> dict:
    """Traffic-mode soak (ISSUE 13): the trace-driven open-loop
    generator (:func:`paddle_tpu.serving.traffic.synth_trace` — tenant
    prefix families, a 4x burst window, mixed priority/deadline/length)
    against an AUTOSCALING, prefill/decode-disaggregated cluster with
    corruption and handoff faults armed:

    - a TAMPER shot on ``handoff_export`` flips real payload bytes —
      the import-side CRC must detect them before install (the request
      then keeps decoding on the prefill replica, token-identically);
    - a TAMPER shot on ``swap_in`` corrupts the first swap payload the
      burst's preemptions produce — detected, quarantined, replayed;
    - an armed raise on ``handoff_import`` is absorbed by the bounded
      idempotent retry (no engine recovery, no double-install);
    - an armed raise on ``autoscale_tick`` skips exactly one scaling
      decision and the loop recovers on the next step.

    Invariants: ZERO lost requests and ZERO duplicated/diverged token
    streams on the surviving (served) request set — gated against
    uninterrupted single-engine references, which the PR 9 cluster
    gates already prove equivalent to any fixed-size cluster; the
    replica count both GREW and SHRANK during the soak (the
    autoscaler's two transitions); every detected corruption was
    quarantined; every surviving replica's allocator drains balanced
    (a retried import that double-installed pages would show here).

    Wired into tier-1 via tests/test_traffic.py::TestTrafficChaosSoak.
    """
    import jax
    from paddle_tpu import observability as obs
    from paddle_tpu.models import llama
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.serving import (AdmissionController,
                                    ClusterAutoscaler, FakeClock,
                                    FaultInjector, ServingCluster,
                                    run_trace, synth_trace)
    from paddle_tpu.serving.traffic import REJECTED_REASONS

    from paddle_tpu.serving import AdapterRegistry, init_lora

    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=64)
    params = llama.init_params(jax.random.key(0), cfg)
    # adapter traffic (ISSUE 14): one shared registry, one fresh
    # 2-slot pool per replica engine — the trace's Zipf-assigned
    # tenant adapters exercise router adapter-affinity, cross-replica
    # loads and slot churn under the same fault/parity gates
    registry = AdapterRegistry(cfg)
    for aid in (1, 2, 3):
        registry.register(aid, init_lora(cfg, 4, seed=200 + aid))

    def factory():
        # host tier + overlap ON: the burst's preemptions swap through
        # the async DMA path, so the armed swap tamper lands on real
        # payload bytes; references stay sync (engine.generate), so
        # the parity gate is also an overlap-identity gate under fire
        return ContinuousBatchingEngine(
            params, cfg, max_batch=2, page_size=8, max_len=48,
            prefill_chunk=8, host_tier=True, overlap=True,
            adapters=dict(slots=2, rank=4, registry=registry))

    # priority-heavy mix + long decodes: the burst's HIGH arrivals
    # must find decode-phase NORMAL/LOW victims in full slots, or the
    # preemption path — and the armed swap-in tamper — never runs
    trace = synth_trace(
        seed=seed, duration_s=duration_s, base_rps=base_rps,
        tenants=3, page_size=8, prefix_pages=2, vocab=cfg.vocab_size,
        burst_mult=5.0, new_tokens=(6, 12),
        priority_weights=(0.3, 0.4, 0.3),
        deadline_frac=0.3, deadline_s=(1.5, 4.0),
        adapters=3)

    was = obs.metrics_enabled()
    obs.REGISTRY.clear()
    obs.enable()
    t_start = time.perf_counter()
    try:
        clock = FakeClock()
        auto = ClusterAutoscaler(
            min_replicas=1, max_replicas=3,
            up_backlog_per_replica=3.0, down_backlog_per_replica=0.5,
            up_after=1, down_after=4, cooldown_ticks=3)
        cluster = ServingCluster(
            factory, replicas=2, prefill_replicas=1, clock=clock,
            autoscaler=auto,
            admission=AdmissionController(tokens_per_s=None),
            retry_sleep=lambda s: None,
            supervisor_kw=dict(backoff_s=0.0, sleep=lambda s: None,
                               circuit_threshold=8, recover_after=8))
        inj = FaultInjector(seed=seed)
        inj.arm_tamper("handoff_export", nth=1)
        inj.arm_tamper("swap_in", nth=1)
        inj.arm("handoff_import", "raise", nth=2)
        inj.arm("autoscale_tick", "raise", nth=4)
        submitted = []
        with inj:
            report = run_trace(
                cluster, trace, clock, step_dt=0.05,
                max_steps=max_steps,
                on_submit=lambda tr, req: submitted.append((tr, req)))
        snap = obs.REGISTRY.to_json()
    finally:
        obs.REGISTRY.clear()
        if not was:
            obs.disable()

    # references AFTER the injector uninstalls (a faulted reference
    # run would gate parity against a poisoned oracle); one engine
    # serves every reference so compiles amortize
    ref_engine = factory()

    # ---- invariants ----
    if report.lost:
        raise SoakError(f"lost requests: {report.lost} finished "
                        f"without a structured reason")
    # door rejections (the one source of truth run_trace scores by)
    # + the scheduler's own expiry: structured DECLINES, no tokens owed
    declined = set(REJECTED_REASONS) | {"deadline_exceeded"}
    mismatched = []
    for tr, req in submitted:
        if not req.done or req.finish_reason is None:
            raise SoakError(f"request {req.rid} not done after drain")
        if req.finish_reason in declined:
            if req.tokens:
                mismatched.append((req.rid, "declined request has "
                                   "tokens"))
            continue
        ref_req = ref_engine.submit(
            tr.prompt, max_new_tokens=tr.max_new_tokens,
            adapter_id=getattr(tr, "adapter_id", 0))
        ref_engine.run()
        ref = np.asarray(ref_req.output)
        if not np.array_equal(req.output, ref):
            mismatched.append((req.rid,
                               "token stream != uninterrupted"))
    if mismatched:
        raise SoakError(f"duplicated/diverged token streams: "
                        f"{mismatched}")
    if not (auto.up_events >= 1 and auto.down_events >= 1):
        raise SoakError(
            f"autoscaler did not breathe: up={auto.up_events} "
            f"down={auto.down_events} (need both transitions)")
    for site in ("handoff_export", "handoff_import", "autoscale_tick"):
        if not inj.fired.get(site):
            raise SoakError(f"cluster site never fired: {site}")
    if cluster.handoff_corruptions_total < 1:
        raise SoakError("the armed handoff tamper was never detected "
                        "by the import-side checksum")
    if cluster.handoff_retries_total < 1:
        raise SoakError("the armed handoff_import fault was never "
                        "absorbed by the bounded retry")
    if cluster.autoscale_faults_total < 1:
        raise SoakError("the armed autoscale_tick fault never fired")
    store = cluster._host_store
    swap_tampers = sum(1 for s, m, _ in inj.log
                       if s == "swap_in" and m == "tamper")
    if swap_tampers and (store is None
                         or store.quarantined_total < swap_tampers):
        raise SoakError(
            f"swap-in tamper fired {swap_tampers}x but only "
            f"{store and store.quarantined_total} payload(s) were "
            f"quarantined — corrupt bytes may have been served")
    unbalanced = {}
    for i, sup in enumerate(cluster.replicas):
        if sup.health == "dead" or sup._draining:
            continue            # drained husks already released
        alloc = sup.engine.cache.allocator
        if sup.engine.cache.prefix is not None:
            sup.engine.cache.prefix.drop_all(alloc)
        st = alloc.stats()
        if st["num_used"] != 0 or \
                st["allocs_total"] != st["frees_total"]:
            unbalanced[i] = st
    if unbalanced:
        raise SoakError(f"allocator unbalanced after drain "
                        f"(double-installed pages?): {unbalanced}")

    return {
        "seed": seed,
        "mode": "traffic",
        "requests": len(submitted),
        "report": report.as_dict(),
        "autoscale": auto.stats(),
        "faults_by_site": {s: n for s, n in inj.fired.items() if n},
        "handoff_corruptions": cluster.handoff_corruptions_total,
        "handoff_retries": cluster.handoff_retries_total,
        "swap_tampers_detected": swap_tampers,
        "quarantined": (store.quarantined_total
                        if store is not None else 0),
        "injected_total": int(sum(
            snap.get("serving_fault_injected_total", {})
            .get("values", {}).values())),
        "elapsed_s": round(time.perf_counter() - t_start, 1),
    }


class _ProcessDied(RuntimeError):
    """The crash harness's simulated ``kill -9``: raised instead of the
    supervisor's in-process recovery, the supervisor object is then
    ABANDONED (no cleanup, no drain — host memory 'gone') and a fresh
    process recovers from the journal directory alone."""


def _crashy(sup):
    """Make ``sup`` die instead of recovering: any step fault now
    escapes as :class:`_ProcessDied` — the harness abandons the object
    and calls ``EngineSupervisor.recover_from_disk``."""
    def die(err):
        raise _ProcessDied(f"{type(err).__name__}: {err}") from err
    sup._on_failure = die
    return sup


def _sweep_env(kv_cache_dtype=None, tp=None, constrained=False,
               spec_k=2, tree=False):
    """One crash-sweep environment: config/params (optionally
    tp-sharded), an engine factory (host tier + adapters + either
    speculation or constrained decoding — the two compose everywhere
    except spec×constraints, which the engine rejects), the job list
    that visits every engine fault site, and per-job uninterrupted
    references. ``tree=True`` (ISSUE 20) swaps the host-speculator
    engine for a draft-model TREE-speculation one, so the
    ``draft_propose``/``tree_verify`` sites get organic per-step
    visits — its references are still exact for every site's recovery
    because tree speculation is token-identical to plain decode."""
    import jax
    from paddle_tpu.models import llama
    from paddle_tpu.inference import ContinuousBatchingEngine
    from paddle_tpu.serving import (AdapterRegistry, HostPageStore,
                                    Priority, init_lora)
    from paddle_tpu.serving.constraints import dfa_from_sequences

    cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=64)
    params = llama.init_params(jax.random.key(0), cfg)
    mesh = None
    if tp:
        from paddle_tpu.distributed.mesh import serving_mesh
        if len(jax.devices()) < tp:
            raise RuntimeError(f"crash sweep tp={tp} needs {tp} devices")
        mesh = serving_mesh(tp)
    registry = AdapterRegistry(cfg)
    for aid in (1, 2, 3):
        registry.register(aid, init_lora(cfg, 4, seed=300 + aid))
    dfa = (dfa_from_sequences(
        [[4, 5, 6, 7, 8, 9], [4, 5, 6, 3, 3, 3]], cfg.vocab_size)
        if constrained else None)

    def factory():
        kw = dict(max_batch=2, page_size=8, max_len=48,
                  prefill_chunk=8, kv_cache_dtype=kv_cache_dtype,
                  host_tier=True, mesh=mesh,
                  adapters=dict(slots=2, rank=4, registry=registry,
                                store=HostPageStore(page_size=8)))
        if constrained:
            kw["constraints"] = True
        elif tree:
            kw.update(spec_k=2, draft_layers=1, spec_tree=(2, 2))
        else:
            kw.update(spec_k=spec_k, speculator=_speculator(spec_k))
        return ContinuousBatchingEngine(params, cfg, **kw)

    rs = np.random.RandomState(7)
    motif = rs.randint(3, cfg.vocab_size, (3,))
    # (prompt, max_new, priority, adapter_id, constraint): a long
    # chunked prefill, a speculative motif, adapter churn over the
    # 2-slot pool (load → demote → promote), then a HIGH burst that
    # preempts decode-phase victims through the swap pair
    jobs = [
        (rs.randint(3, cfg.vocab_size, (18,)).astype(np.int32), 4,
         Priority.NORMAL, 1, None),
        (np.tile(motif, 5).astype(np.int32)[:14], 5,
         Priority.NORMAL, 2, dfa),
        (rs.randint(3, cfg.vocab_size, (6,)).astype(np.int32), 5,
         Priority.NORMAL, 3, None),
        (rs.randint(3, cfg.vocab_size, (5,)).astype(np.int32), 4,
         Priority.NORMAL, 1, None),
        (rs.randint(3, cfg.vocab_size, (4,)).astype(np.int32), 2,
         Priority.HIGH, 0, None),
        (rs.randint(3, cfg.vocab_size, (7,)).astype(np.int32), 4,
         Priority.NORMAL, 0, None),
    ]
    ref_engine = factory()
    refs = []
    for p, m, _prio, aid, con in jobs:
        r = ref_engine.submit(p, max_new_tokens=m, adapter_id=aid,
                              constraint=con)
        ref_engine.run()
        refs.append(np.asarray(r.output))
    return factory, jobs, refs, dfa


def run_crash_sweep(sites=None, kv_cache_dtype=None, tp=None,
                    constrained=False, checkpoint_every=3,
                    max_steps: int = 4000, wal_root=None) -> dict:
    """The HEADLINE crash-point sweep (ISSUE 15): for each engine
    fault site, arm one raise, drive a crash-on-fault supervisor until
    the 'process dies' at that exact site, abandon it, and
    ``recover_from_disk`` — every acked request must finish
    TOKEN-IDENTICAL to its uninterrupted reference, zero
    lost/duplicated, allocator balanced, and the armed site must have
    actually fired. ``constrained=True`` swaps the speculative engine
    for a constrained+adapter one (spec×constraints is rejected by the
    engine), covering mid-grammar sessions on the same gate."""
    import tempfile

    from paddle_tpu.serving import (EngineSupervisor, FaultInjector,
                                    InjectedFault)
    from paddle_tpu.serving.resilience import ENGINE_SITES

    # the draft_propose / tree_verify sites (ISSUE 20) only execute on
    # a draft-model tree-speculation engine, so the sweep swaps in the
    # tree environment for exactly those sites (built lazily — a
    # sites= list that never names them pays nothing); everything else
    # keeps the host-speculator env. References are interchangeable:
    # both engines are token-identical to plain decode.
    tree_sites = ("draft_propose", "tree_verify")
    envs = {False: _sweep_env(
        kv_cache_dtype=kv_cache_dtype, tp=tp, constrained=constrained)}
    if sites is None:
        sites = list(ENGINE_SITES)
        if constrained:
            # a constrained engine rejects spec_k > 0, so neither the
            # verify program nor the draft/tree path ever runs — the
            # speculative sweep owns those sites
            sites = [s for s in sites
                     if s not in ("verify_step",) + tree_sites]
    root = wal_root or tempfile.mkdtemp(prefix="crash_sweep_")
    per_site = {}
    for site in sites:
        tree = site in tree_sites
        if tree and tree not in envs:
            envs[tree] = _sweep_env(kv_cache_dtype=kv_cache_dtype,
                                    tp=tp, tree=True)
        factory, jobs, refs, _dfa = envs[tree]
        wd = os.path.join(root, f"{site}-{kv_cache_dtype or 'fp'}"
                          + (f"-tp{tp}" if tp else "")
                          + ("-con" if constrained else ""))
        sup_kw = dict(backoff_s=0.0, sleep=lambda s: None,
                      circuit_threshold=50, wal_dir=wd,
                      checkpoint_every=checkpoint_every,
                      wal_kw=dict(group_interval_s=0.0))
        sup = _crashy(EngineSupervisor(factory, **sup_kw))
        inj = FaultInjector(seed=0)
        # sites behind a bounded in-place retry (the ISSUE 13 swap-in
        # retry) absorb a single shot without the process ever dying —
        # arm enough consecutive shots to exhaust the retry budget so
        # the kill actually lands
        shots = (sup.engine.cache.swap_in_retries + 1
                 if site == "swap_in" else 1)
        for k in range(shots):
            inj.arm(site, "raise", nth=k + 1)
        job_of = {}                 # rid -> job index (set at ack)
        cur = {}                    # rid -> live handle (recoveries
        #                             supersede the dead object)
        deaths = 0
        steps = 0

        def recover():
            nonlocal sup, deaths
            deaths += 1
            sup = _crashy(EngineSupervisor.recover_from_disk(
                factory, wd, **{k: v for k, v in sup_kw.items()
                                if k != "wal_dir"}))
            cur.update(sup.restored)

        with inj:
            for i, (p, m, prio, aid, con) in enumerate(jobs):
                while True:
                    try:
                        r = sup.submit(p, max_new_tokens=m,
                                       priority=prio, adapter_id=aid,
                                       constraint=con)
                        job_of[r.rid] = i
                        cur[r.rid] = r
                        break
                    except (InjectedFault, _ProcessDied):
                        # write-ahead append died BEFORE the ack: the
                        # client never got a handle — recover and
                        # resubmit, like any client retry
                        recover()
                for _ in range(2):
                    try:
                        sup.step()
                    except _ProcessDied:
                        recover()
                    steps += 1
            while True:
                try:
                    if not sup.step():
                        break
                except _ProcessDied:
                    recover()
                steps += 1
                if steps >= max_steps:
                    raise SoakError(f"[{site}] sweep did not drain "
                                    f"within {max_steps} steps")
        by_job = {j: cur[rid] for rid, j in job_of.items()}
        if not inj.fired.get(site):
            raise SoakError(f"[{site}] armed site never fired — the "
                            f"sweep workload does not visit it")
        if deaths < 1:
            raise SoakError(
                f"[{site}] the site fired but the process never died "
                f"— the kill was absorbed before it could land")
        # flight-recorder gate (ISSUE 16): every simulated kill must
        # leave a parseable CRC-framed black box next to the WAL
        from paddle_tpu.observability import flight as _flight
        dumps = _flight.find_dumps(wd)
        if len(dumps) < deaths:
            raise SoakError(
                f"[{site}] {deaths} death(s) but only {len(dumps)} "
                f"flight dump(s) in {wd} — a kill left no black box")
        for dp in dumps:
            _flight.load(dp)    # raises on CRC mismatch / torn dump
        for j, req in by_job.items():
            if not req.done or req.finish_reason not in ("eos",
                                                         "max_len"):
                raise SoakError(
                    f"[{site}] job {j} lost: done={req.done} "
                    f"reason={req.finish_reason}")
            if not np.array_equal(np.asarray(req.output), refs[j]):
                raise SoakError(
                    f"[{site}] job {j} diverged after recovery: "
                    f"{req.output} vs {refs[j]}")
        if len(by_job) != len(jobs):
            raise SoakError(f"[{site}] {len(jobs) - len(by_job)} "
                            f"job(s) never acked")
        alloc = sup.engine.cache.allocator
        if sup.engine.cache.prefix is not None:
            sup.engine.cache.prefix.drop_all(alloc)
        st = alloc.stats()
        if st["num_used"] != 0:
            raise SoakError(f"[{site}] allocator unbalanced after "
                            f"drain: {st}")
        if sup.engine.draft_cache is not None:
            dst = sup.engine.draft_cache.allocator.stats()
            if dst["num_used"] != 0:
                raise SoakError(f"[{site}] DRAFT pool unbalanced "
                                f"after drain: {dst}")
        per_site[site] = {"deaths": deaths,
                          "fired": int(inj.fired[site]),
                          "flight_dumps": len(dumps),
                          "last_flight_dump": dumps[-1]}
    return {"mode": "crash_sweep", "tier": kv_cache_dtype or "fp",
            "tp": tp, "constrained": constrained,
            "sites": per_site}


def run_crash_soak(seed: int = 0, kills: int = 4,
                   max_steps: int = 8000, wal_root=None) -> dict:
    """Randomized crash soak (ISSUE 15 CI satellite): a seeded
    workload against a WAL-backed supervisor, the 'process' killed
    after a RANDOM armed site (one kill is a torn-write tamper — half
    a frame reaches disk), recovered from the journal directory each
    time, with the standing zero-lost/zero-duplicated +
    token-identity + balanced-allocator gates at the end. Wired into
    tier-1 via tests/test_wal.py::TestCrashSoak."""
    import tempfile

    from paddle_tpu.serving import (EngineSupervisor, FaultInjector,
                                    InjectedFault)
    from paddle_tpu.serving.resilience import ENGINE_SITES

    factory, jobs, refs, _dfa = _sweep_env()
    rs = np.random.RandomState(seed)
    wd = os.path.join(wal_root or tempfile.mkdtemp(prefix="crash_soak_"),
                      "journal")
    sup_kw = dict(backoff_s=0.0, sleep=lambda s: None,
                  circuit_threshold=50, wal_dir=wd, checkpoint_every=4,
                  wal_kw=dict(group_interval_s=0.0))
    sup = _crashy(EngineSupervisor(factory, **sup_kw))
    inj = FaultInjector(seed=seed)
    # frequently-visited sites so every armed kill actually lands;
    # the per-site sweep (run_crash_sweep) owns exhaustive coverage
    kill_sites = [s for s in ENGINE_SITES
                  if s in ("decode_step", "prefill_chunk", "sched_tick",
                           "transfer", "dispatch", "commit",
                           "wal_append", "wal_fsync",
                           "checkpoint_write")]
    job_of = {}                     # rid -> job index (set at ack)
    cur = {}                        # rid -> live handle
    deaths = 0
    steps = 0

    def recover():
        nonlocal sup, deaths
        deaths += 1
        sup = _crashy(EngineSupervisor.recover_from_disk(
            factory, wd, **{k: v for k, v in sup_kw.items()
                            if k != "wal_dir"}))
        cur.update(sup.restored)

    job_stream = [jobs[i % len(jobs)] for i in range(3 * len(jobs))]
    armed = 0
    with inj:
        for i, (p, m, prio, aid, con) in enumerate(job_stream):
            if armed < kills and i % 4 == 1:
                if armed == kills - 1:
                    inj.arm_tamper("wal_append",
                                   nth=int(rs.randint(1, 4)))
                else:
                    inj.arm(str(rs.choice(kill_sites)), "raise",
                            nth=int(rs.randint(1, 6)))
                armed += 1
            while True:
                try:
                    r = sup.submit(p, max_new_tokens=m, priority=prio,
                                   adapter_id=aid, constraint=con)
                    job_of[r.rid] = i % len(jobs)
                    cur[r.rid] = r
                    break
                except (InjectedFault, _ProcessDied):
                    recover()
            for _ in range(2):
                try:
                    sup.step()
                except _ProcessDied:
                    recover()
                steps += 1
        while True:
            try:
                if not sup.step():
                    break
            except _ProcessDied:
                recover()
            steps += 1
            if steps >= max_steps:
                raise SoakError(f"crash soak did not drain within "
                                f"{max_steps} steps")
    if deaths < 1:
        raise SoakError("no armed kill ever landed — the soak "
                        "exercised nothing")
    # flight-recorder gate (ISSUE 16): every kill left a black box,
    # and every box loads back CRC-clean
    from paddle_tpu.observability import flight as _flight
    flight_dumps = _flight.find_dumps(wd)
    if len(flight_dumps) < deaths:
        raise SoakError(
            f"{deaths} death(s) but only {len(flight_dumps)} flight "
            f"dump(s) in {wd} — a kill left no black box")
    for dp in flight_dumps:
        _flight.load(dp)        # raises on CRC mismatch / torn dump
    final = {rid: (cur[rid], j) for rid, j in job_of.items()}
    lost = [rid for rid, (req, _j) in final.items()
            if not req.done or req.finish_reason not in ("eos",
                                                         "max_len")]
    if lost:
        raise SoakError(f"lost requests after crash soak: {lost}")
    mism = [rid for rid, (req, j) in final.items()
            if not np.array_equal(np.asarray(req.output), refs[j])]
    if mism:
        raise SoakError(f"duplicated/diverged token streams: {mism}")
    alloc = sup.engine.cache.allocator
    if sup.engine.cache.prefix is not None:
        sup.engine.cache.prefix.drop_all(alloc)
    st = alloc.stats()
    if st["num_used"] != 0:
        raise SoakError(f"allocator unbalanced after drain: {st}")
    return {"seed": seed, "mode": "crash", "deaths": deaths,
            "requests": len(final), "steps": steps,
            "faults_by_site": {s: n for s, n in inj.fired.items()
                               if n},
            "flight_dumps": len(flight_dumps),
            "last_flight_dump": flight_dumps[-1],
            "wal_stats": sup.wal.stats()}


def run_multiproc_soak(seed: int = 0, requests: int = 6,
                       max_steps: int = 600, workdir=None,
                       xla_cache_dir=None) -> dict:
    """Multi-process soak (ISSUE 19): a REAL process tree — one
    prefill worker, one decode worker, one shared KV fabric server —
    driven by :class:`~paddle_tpu.serving.MultiProcessCluster` with
    chaos armed at the controller's wire seams:

    - a TAMPER shot on ``handoff_export`` flips real payload bytes in
      a cross-process KV handoff — the decode-side CRC verifier must
      refuse the install (nothing committed) and the request must
      finish on its prefill replica token-identically;
    - armed ``rpc_send`` / ``rpc_recv`` transport faults drop frames
      mid-call — the bounded idempotent retry plus the server-side
      dedupe cache must absorb them with zero duplicate execution;
    - the decode worker is ``SIGKILL``ed once it owns decoded tokens —
      failover spawns a replacement on the same WAL dir and the
      recovered sessions resume mid-stream.

    Invariants: zero lost / duplicated requests (every token stream
    EXACTLY equals its uninterrupted in-process single-engine
    reference), the corruption was detected (never installed), every
    armed transport fault actually fired, the fabric served demotes,
    and both surviving workers drain to balanced allocators
    (``num_used == 0`` once the standing prefix pages are dropped).
    Wired into tier-1 via tests/test_multiproc.py (conftest-ordered
    dead last; spawn count budgeted for the 870s watchdog).
    """
    import signal
    import tempfile

    from paddle_tpu.serving import FaultInjector
    from paddle_tpu.serving.multiproc import (FabricProcess,
                                              MultiProcessCluster)
    from paddle_tpu.serving.node import tiny_llama_engine

    rs = np.random.RandomState(seed)
    sys_prompt = rs.randint(3, 256, (12,)).astype(np.int32)
    jobs = []
    for _ in range(requests):
        tail = rs.randint(3, 256,
                          (int(rs.randint(2, 7)),)).astype(np.int32)
        jobs.append((np.concatenate([sys_prompt, tail]),
                     int(rs.randint(3, 6))))
    # uninterrupted single-engine references: the factory builds
    # bit-identical weights from the seed in every process, and
    # per-request greedy decode is batch-composition-independent, so
    # routing cannot change any stream
    ref_engine = tiny_llama_engine()()
    refs = [np.asarray(ref_engine.generate([p], max_new_tokens=m)[0])
            for p, m in jobs]

    wd = workdir or tempfile.mkdtemp(prefix="mp_soak_")
    if xla_cache_dir is None:
        xla_cache_dir = os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "artifacts", "xla_cache")
    t_start = time.perf_counter()
    fp = None
    mc = None
    inj = FaultInjector(seed=seed)
    try:
        fp = FabricProcess(wd, page_size=8)
        mc = MultiProcessCluster(
            replicas=2, prefill_replicas=1,
            workdir=os.path.join(wd, "cluster"), fabric=fp.endpoint,
            xla_cache_dir=xla_cache_dir)
        reqs = [mc.submit(p, max_new_tokens=m) for p, m in jobs]
        with inj:
            # first handoff export ships corrupt bytes; a mid-run send
            # and recv each drop a frame (site counts are RPC calls,
            # so double-digit nth lands a few steps in)
            inj.arm_tamper("handoff_export", nth=1)
            inj.arm("rpc_send", "raise", nth=7)
            inj.arm("rpc_recv", "raise", nth=19)
            killed = False
            steps = 0
            while mc.step():
                steps += 1
                if not killed and any(
                        len(r.tokens) >= 2
                        and mc._owner.get(r.rid) == 1
                        for r in reqs if not r.done):
                    os.kill(mc.nodes[1].proc.pid, signal.SIGKILL)
                    killed = True
                if steps >= max_steps:
                    raise SoakError(f"multiproc soak did not drain "
                                    f"within {max_steps} steps")

        # ---- invariants ----
        if not killed:
            raise SoakError("the decode worker never owned tokens — "
                            "the SIGKILL gate was not exercised")
        if mc.failovers_total < 1:
            raise SoakError("SIGKILL did not surface as a failover")
        if mc.handoff_corruptions_total < 1:
            raise SoakError("the tampered handoff payload was not "
                            "detected by the decode-side CRC gate")
        for site in ("rpc_send", "rpc_recv"):
            if not inj.fired.get(site):
                raise SoakError(f"armed {site} fault never fired — "
                                f"the transport retry path was not "
                                f"exercised")
        lost = [r.rid for r in reqs
                if not r.done or r.finish_reason not in ("eos",
                                                         "max_len")]
        if lost:
            raise SoakError(f"lost requests after drain: {lost}")
        mism = [r.rid for r, ref in zip(reqs, refs)
                if not np.array_equal(np.asarray(r.output), ref)]
        if mism:
            raise SoakError(
                f"duplicated/diverged token streams: {mism}")
        unbalanced = {}
        for i in range(len(mc.nodes)):
            st, _ = mc.nodes[i].call("tier_stats",
                                     {"drop_prefix": True})
            alloc = st["allocator"]
            if alloc["num_used"] != 0 or \
                    alloc["allocs_total"] != alloc["frees_total"]:
                unbalanced[i] = alloc
        if unbalanced:
            raise SoakError(f"allocator unbalanced after drain: "
                            f"{unbalanced}")
        fc = fp.client()
        fab_stats, _ = fc.call("stats")
        fc.close()
        if fab_stats["puts_total"] < 1:
            raise SoakError("the fabric never saw a demote — the "
                            "shared tier was not exercised")
        return {"seed": seed, "mode": "multiproc",
                "requests": len(reqs), "steps": steps,
                "failovers": mc.failovers_total,
                "handoffs": mc.handoffs_total,
                "handoff_corruptions": mc.handoff_corruptions_total,
                "faults_by_site": {s: n for s, n in inj.fired.items()
                                   if n},
                "fabric": {k: fab_stats[k]
                           for k in ("puts_total", "hits_total",
                                     "misses_total", "entries")},
                "elapsed_s": round(time.perf_counter() - t_start, 1)}
    finally:
        if mc is not None:
            mc.close()
        if fp is not None:
            fp.close()


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--faults", type=int, default=50,
                    help="minimum injected faults across all sites")
    ap.add_argument("--requests", type=int, default=24)
    ap.add_argument("--cluster", action="store_true",
                    help="cluster mode: kill a random replica "
                         "mid-soak, assert zero lost/duplicated "
                         "requests cluster-wide + affinity recovery")
    ap.add_argument("--replicas", type=int, default=3,
                    help="cluster-mode replica count")
    ap.add_argument("--crash", action="store_true",
                    help="crash mode (ISSUE 15): seeded workload, "
                         "process-death simulation after random armed "
                         "sites (incl. a torn WAL write), "
                         "recover-from-disk each time; asserts zero "
                         "lost/duplicated + token identity")
    ap.add_argument("--kills", type=int, default=4,
                    help="crash-mode simulated process deaths")
    ap.add_argument("--tp2d", action="store_true",
                    help="single-engine soak on a tp=2 x dp=2 serving "
                         "mesh (ISSUE 17); references stay "
                         "single-chip, so the parity gate doubles as "
                         "the 2-D-mesh identity gate under fault "
                         "fire (needs 4 devices)")
    ap.add_argument("--multiproc", action="store_true",
                    help="multi-process mode (ISSUE 19): a real "
                         "2-replica + fabric process tree; SIGKILL "
                         "the decode worker mid-soak, tamper a wire "
                         "handoff, drop RPC frames; asserts zero "
                         "lost/duplicated requests, every corruption "
                         "detected, balanced allocators")
    ap.add_argument("--traffic", action="store_true",
                    help="traffic mode (ISSUE 13): trace-driven "
                         "open-loop load against an autoscaling "
                         "cluster with corruption + handoff faults "
                         "armed; asserts zero lost/duplicated "
                         "requests and that the replica count both "
                         "grew and shrank")
    args = ap.parse_args()
    if args.multiproc:
        report = run_multiproc_soak(seed=args.seed,
                                    requests=args.requests)
        print(json.dumps(report, indent=2))
        print("chaos_soak: OK — decode worker SIGKILLed and replaced "
              "from its WAL dir, corrupt wire handoff detected, "
              "dropped RPC frames absorbed by bounded retry, zero "
              "lost/duplicated requests, balanced allocators",
              file=sys.stderr)
        return 0
    if args.crash:
        report = run_crash_soak(seed=args.seed, kills=args.kills)
        print(json.dumps(report, indent=2))
        print("chaos_soak: OK — process died and recovered from disk "
              f"{report['deaths']}x, zero lost/duplicated requests, "
              "token-identical streams, balanced allocator",
              file=sys.stderr)
        return 0
    if args.traffic:
        report = run_traffic_soak(seed=args.seed)
        print(json.dumps(report, indent=2))
        print("chaos_soak: OK — autoscaled up and down under the "
              "trace, every corruption detected+quarantined, zero "
              "lost/duplicated requests", file=sys.stderr)
        return 0
    if args.cluster:
        report = run_cluster_soak(seed=args.seed,
                                  requests=args.requests,
                                  replicas=args.replicas)
        print(json.dumps(report, indent=2))
        print("chaos_soak: OK — replica killed and rebuilt, zero "
              "lost/duplicated requests cluster-wide, affinity "
              "recovered", file=sys.stderr)
        return 0
    kw = dict(tp=2, dp=2) if args.tp2d else {}
    report = run_soak(seed=args.seed, faults=args.faults,
                      requests=args.requests, **kw)
    print(json.dumps(report, indent=2))
    print("chaos_soak: OK — zero lost/duplicated requests, balanced "
          "allocator, all sites faulted"
          + (" (tp=2 x dp=2 mesh)" if args.tp2d else ""),
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
