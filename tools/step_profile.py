"""Measured component breakdown of the bench train step on a live chip.

Times separately-jitted slices of the headline config (660M Llama,
batch 4 x seq 4096) with host-transfer fences, then prints a markdown
table of step-time shares. One-off tuning/analysis tool — feeds
PERF_NOTES.md (the MFU ceiling accounting), not the driver flow.

  python tools/step_profile.py            # on the real chip
"""
import dataclasses
import sys
import time

import numpy as np
import jax
import jax.numpy as jnp

sys.path.insert(0, ".")


def timed(fn, fence, iters=6):
    fence(fn())              # compile + warm
    fence(fn())
    best = float("inf")
    for _ in range(iters):
        t0 = time.perf_counter()
        fence(fn())
        best = min(best, time.perf_counter() - t0)
    return best


def fence_tree(tree):
    leaf = jax.tree_util.tree_leaves(tree)[0]
    return float(jnp.sum(leaf[..., 0].astype(jnp.float32)))


def main():
    from paddle_tpu.models import llama, train

    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        cfg = llama.LlamaConfig(
            vocab_size=32000, hidden_size=1536, intermediate_size=4096,
            num_layers=20, num_heads=12, num_kv_heads=12,
            max_seq_len=4096, dtype=jnp.bfloat16, remat=True)
        batch, seq, chunk = 4, 4096, 512
    else:  # smoke path
        cfg = llama.LlamaConfig.tiny(num_layers=2, max_seq_len=256)
        batch, seq, chunk = 2, 256, None

    step = train.make_train_step(cfg, seq_chunk=chunk)
    state = jax.jit(lambda k: train.init_train_state(k, cfg))(
        jax.random.key(0))
    tokens = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (batch, seq)), jnp.int32)

    # 1) full train step (fwd + bwd + AdamW; state is donated, so thread
    # it through a holder)
    hold = {"s": state}

    def full():
        hold["s"], m = step(hold["s"], tokens)
        return m
    t_full = timed(full, lambda m: float(m["loss"]))
    state = jax.jit(lambda k: train.init_train_state(k, cfg))(
        jax.random.key(0))

    # 2) grads-only (fwd + bwd, no clip/optimizer)
    def loss(p, t):
        return llama.loss_fn(p, t, cfg, None, seq_chunk=chunk)
    gradfn = jax.jit(jax.grad(loss))
    t_grad = timed(lambda: gradfn(state.params, tokens), fence_tree)

    # 3) fwd-only loss
    lossfn = jax.jit(loss)
    t_fwd = timed(lambda: lossfn(state.params, tokens), float)

    # 4) embed + final-norm + logits + CE alone: the same program with
    # zero decoder layers (isolates the 32000-vocab head + embedding)
    cfg0 = dataclasses.replace(cfg, num_layers=0)
    p0 = jax.jit(lambda k: llama.init_params(k, cfg0))(jax.random.key(0))
    headfn = jax.jit(lambda p, t: llama.loss_fn(p, t, cfg0, None,
                                                seq_chunk=chunk))
    t_head = timed(lambda: headfn(p0, tokens), float)
    headgrad = jax.jit(jax.grad(lambda p, t: llama.loss_fn(
        p, t, cfg0, None, seq_chunk=chunk)))
    t_headg = timed(lambda: headgrad(p0, tokens), fence_tree)

    # 5) clip + AdamW update alone over real-shaped grads, at the train
    # step's OWN default hyperparameters (read, not copied — so this
    # cannot drift from the math the full step actually runs)
    import inspect
    hp = {k: p.default for k, p in
          inspect.signature(train.make_train_step).parameters.items()
          if p.default is not inspect.Parameter.empty}
    grads = jax.tree.map(lambda p: jnp.ones_like(p, jnp.float32),
                         state.params)

    def optonly(state, grads):
        gnorm = jnp.sqrt(sum(jnp.sum(g * g)
                             for g in jax.tree.leaves(grads)))
        scale = jnp.minimum(1.0, hp["grad_clip"] / (gnorm + 1e-6))
        grads = jax.tree.map(lambda g: g * scale, grads)

        def upd(g, p32, m, v):
            return train._adamw(g, p32, m, v, state.step, hp["lr"],
                                hp["b1"], hp["b2"], hp["eps"],
                                hp["weight_decay"])
        out = jax.tree.map(upd, grads, state.master, state.m, state.v)
        return jax.tree.map(lambda t: t[0], out,
                            is_leaf=lambda x: isinstance(x, tuple))
    optfn = jax.jit(optonly)
    t_opt = timed(lambda: optfn(state, grads), fence_tree)

    rows = [
        ("full step (fwd+bwd+clip+AdamW)", t_full),
        ("fwd+bwd only", t_grad),
        ("fwd only", t_fwd),
        ("embed+head fwd (0-layer model)", t_head),
        ("embed+head fwd+bwd (0-layer model)", t_headg),
        ("clip+AdamW update only", t_opt),
    ]
    print("\n| slice | ms | share of full |")
    print("|---|---|---|")
    for name, t in rows:
        print(f"| {name} | {t * 1e3:.0f} | {100 * t / t_full:.0f}% |")
    toks = batch * seq
    print(f"\ntokens/s full step: {toks / t_full:,.0f}")
    print(f"decoder-layers fwd (fwd - head): "
          f"{1e3 * (t_fwd - t_head):.0f} ms; bwd overhead "
          f"(grad - fwd): {1e3 * (t_grad - t_fwd):.0f} ms; "
          f"opt by subtraction (full - grad): "
          f"{1e3 * (t_full - t_grad):.0f} ms")

    # profiler summary tables (host spans + device op/category tables
    # from the jax.profiler trace) — the per-XLA-op ranking that feeds
    # the MFU residual accounting in PERF_NOTES.md
    try:
        profiled_summary(step, hold["s"], tokens)
    except Exception as e:     # analysis extra; never kill the timings
        print(f"profiler summary skipped: {type(e).__name__}: {e}")


def profiled_summary(step, state, tokens, record_steps=2):
    """Run the fused step under the Profiler with a device trace and
    print Profiler.summary()'s ranked tables."""
    import os
    import tempfile
    import paddle_tpu.profiler as profiler

    os.environ["PADDLE_TPU_DEVICE_TRACE"] = "1"
    os.environ.setdefault("PADDLE_TPU_DEVICE_TRACE_DIR",
                          tempfile.mkdtemp(prefix="pt_trace_"))
    hold = {"s": state}
    prof = profiler.Profiler(scheduler=(1, 1 + record_steps))
    prof.start()
    for _ in range(1 + record_steps):
        with profiler.RecordEvent("fused_train_step", "Operator"):
            hold["s"], m = step(hold["s"], tokens)
            jax.block_until_ready(m["loss"])
        prof.step()
    prof.stop()
    print()
    print(prof.summary(time_unit="ms"))


if __name__ == "__main__":
    main()
